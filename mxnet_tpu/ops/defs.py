"""Core operator corpus (tensor/math ops).

Reference surface: ``src/operator/tensor/**`` (SURVEY.md §3.1 "Operator
corpus": elemwise unary/binary with mshadow functors, broadcast/reduce,
dot/batch_dot, matrix_op, indexing, ordering, init ops).  Here every op is a
pure jax function registered via ``@op`` (see registry.py); gradients come
from jax.vjp, kernels from XLA — there is no mshadow/cuDNN analog to write.

Naming follows the reference ``mx.nd.*`` API so user code ports unchanged.
NN ops (Convolution, BatchNorm, ...) live in ops/nn.py.
"""
from __future__ import annotations

import builtins
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import op, register, invoke, alias, get_op

_abs = builtins.abs
_sum = builtins.sum
_max = builtins.max
_min = builtins.min
_round = builtins.round


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


# ======================================================================= #
# elementwise unary
# ======================================================================= #

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "round": jnp.round, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log,
    "log10": jnp.log10, "log2": jnp.log2, "log1p": jnp.log1p,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "arcsin": jnp.arcsin,
    "arccos": jnp.arccos, "arctan": jnp.arctan, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "tanh": jnp.tanh, "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "negative": jnp.negative, "reciprocal": lambda x: 1.0 / x,
    "rsqrt": lax.rsqrt, "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}

_g = globals()
for _name, _fn in _UNARY.items():
    def _make(f):
        def impl(data):
            return f(data)
        return impl
    _impl = _make(_fn)
    _impl.__name__ = _name
    _g[_name] = op(_name)(_impl)

alias("_copy", "identity")
abs = _g["abs"]  # noqa: A001
round = _g["round"]  # noqa: A001


@op("softrelu")
def softrelu(data):
    return jax.nn.softplus(data)


@op("BlockGrad", differentiable=True)
def BlockGrad(data):
    return lax.stop_gradient(data)


def stop_gradient(data):
    return BlockGrad(data)


@op("shape_array", differentiable=False)
def shape_array(data):
    return jnp.asarray(data.shape, jnp.int32)


@op("size_array", differentiable=False)
def size_array(data):
    return jnp.asarray([data.size], jnp.int32)


@op("cast")
def cast(data, *, dtype):
    return data.astype(jnp.dtype(dtype))


alias("Cast", "cast")


@op("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * data * data, a - 0.5 / s2)


# ======================================================================= #
# elementwise binary (broadcasting); MXNet has both elemwise_* (no
# broadcast) and broadcast_* families — jnp broadcasts, so they share impls
# ======================================================================= #

_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}
for _name, _fn in _BINARY.items():
    def _makeb(f):
        def impl(lhs, rhs):
            return f(lhs, rhs)
        return impl
    _impl = _makeb(_fn)
    _impl.__name__ = _name
    _g[_name] = op(_name)(_impl)

for _short, _long in [("add", "broadcast_add"), ("subtract", "broadcast_sub"),
                      ("multiply", "broadcast_mul"), ("divide", "broadcast_div"),
                      ("modulo", "broadcast_mod"), ("power", "broadcast_power"),
                      ("maximum", "broadcast_maximum"),
                      ("minimum", "broadcast_minimum"),
                      ("elemwise_add", "broadcast_add"),
                      ("elemwise_sub", "broadcast_sub"),
                      ("elemwise_mul", "broadcast_mul"),
                      ("elemwise_div", "broadcast_div")]:
    alias(_short, _long)
    _g[_short] = _g[_long]

_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}
for _name, _fn in _CMP.items():
    def _makec(f):
        def impl(lhs, rhs):
            out = f(lhs, rhs)
            # MXNet comparison ops return the input float dtype (1.0/0.0)
            dt = jnp.result_type(lhs, rhs)
            if jnp.issubdtype(dt, jnp.bool_):
                dt = jnp.float32
            return out.astype(dt)
        return impl
    _impl = _makec(_fn)
    _impl.__name__ = _name
    _g[_name] = op(_name, differentiable=False)(_impl)

for _short, _long in [("equal", "broadcast_equal"),
                      ("not_equal", "broadcast_not_equal"),
                      ("greater", "broadcast_greater"),
                      ("greater_equal", "broadcast_greater_equal"),
                      ("lesser", "broadcast_lesser"),
                      ("lesser_equal", "broadcast_lesser_equal"),
                      ("logical_and", "broadcast_logical_and"),
                      ("logical_or", "broadcast_logical_or"),
                      ("logical_xor", "broadcast_logical_xor")]:
    alias(_short, _long)
    _g[_short] = _g[_long]


@op("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@op("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool) if condition.dtype != bool
                     else condition, x, y)


@op("clip")
def clip(data, *, a_min, a_max):
    return jnp.clip(data, a_min, a_max)


@op("add_n", variadic=True)
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


alias("ElementWiseSum", "add_n")


# ======================================================================= #
# reductions
# ======================================================================= #

@op("sum")
def sum(data, *, axis=None, keepdims=False, exclude=False):  # noqa: A001
    return jnp.sum(data, axis=_excl(_norm_axis(axis), data.ndim, exclude),
                   keepdims=keepdims)


def _excl(axis, ndim, exclude):
    if not exclude or axis is None:
        return axis
    ax = (axis,) if isinstance(axis, int) else axis
    ax = tuple(a % ndim for a in ax)
    return tuple(i for i in range(ndim) if i not in ax)


@op("mean")
def mean(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.mean(data, axis=_excl(_norm_axis(axis), data.ndim, exclude),
                    keepdims=keepdims)


@op("prod")
def prod(data, *, axis=None, keepdims=False):
    return jnp.prod(data, axis=_norm_axis(axis), keepdims=keepdims)


@op("nansum")
def nansum(data, *, axis=None, keepdims=False):
    return jnp.nansum(data, axis=_norm_axis(axis), keepdims=keepdims)


@op("nanprod")
def nanprod(data, *, axis=None, keepdims=False):
    return jnp.nanprod(data, axis=_norm_axis(axis), keepdims=keepdims)


@op("max")
def max(data, *, axis=None, keepdims=False):  # noqa: A001
    return jnp.max(data, axis=_norm_axis(axis), keepdims=keepdims)


@op("min")
def min(data, *, axis=None, keepdims=False):  # noqa: A001
    return jnp.min(data, axis=_norm_axis(axis), keepdims=keepdims)


@op("norm")
def norm(data, *, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    if ord != 2:
        raise MXNetError(f"norm: only ord=1 or 2 supported, got {ord}")
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@op("argmax", differentiable=False)
def argmax(data, *, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@op("argmin", differentiable=False)
def argmin(data, *, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@op("argmax_channel", differentiable=False)
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ======================================================================= #
# ordering
# ======================================================================= #

@op("topk", differentiable=False)
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    x = data if not is_ascend else -data
    x = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(x, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx.astype(jnp.dtype(dtype)))
    if ret_typ == "mask":
        mask = jnp.zeros(jnp.moveaxis(data, axis, -1).shape, data.dtype)
        mask = jnp.put_along_axis(
            mask, jnp.moveaxis(idx, axis, -1), 1.0, axis=-1,
            inplace=False)
        return jnp.moveaxis(mask, -1, axis)
    return idx.astype(jnp.dtype(dtype))


@op("sort")
def sort(data, *, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@op("argsort", differentiable=False)
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


# ======================================================================= #
# linalg
# ======================================================================= #

@op("dot")
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    """MXNet dot: contract lhs's last axis with rhs's first (reference
    ``src/operator/tensor/dot.cc``); transpose flags flip which axis is
    contracted.  The 2-D case is the MXU matmul hot path."""
    a, b = lhs, rhs
    if transpose_a and a.ndim > 1:
        a = jnp.transpose(a)  # full axis reversal, per reference semantics
    if transpose_b and b.ndim > 1:
        b = jnp.transpose(b)
    if a.ndim == 0 or b.ndim == 0:
        return a * b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=([-1], [0]))


@op("batch_dot")
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@op("matmul")
def matmul(lhs, rhs):
    return jnp.matmul(lhs, rhs)


@op("linalg_gemm2")
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@op("linalg_syrk")
def linalg_syrk(A, *, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@op("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@op("linalg_trsm")
def linalg_trsm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular solve (reference ``linalg_trsm``).  jax's
    solve_triangular is left-side only, so the right-side form
    ``X·op(A) = alpha·B`` solves the transposed system
    ``op(A)^T·X^T = alpha·B^T``."""
    if not rightside:
        return jax.scipy.linalg.solve_triangular(
            A, B * alpha, trans=1 if transpose else 0, lower=lower)
    xt = jax.scipy.linalg.solve_triangular(
        A, jnp.swapaxes(B * alpha, -1, -2),
        trans=0 if transpose else 1, lower=lower)
    return jnp.swapaxes(xt, -1, -2)


@op("L2Normalization")
def L2Normalization(data, *, eps=1e-10, mode="instance"):
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    else:  # spatial
        ax = tuple(range(2, data.ndim))
    n = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / n


# ======================================================================= #
# shape manipulation
# ======================================================================= #

@op("reshape")
def reshape(data, *, shape):
    return jnp.reshape(data, _mx_reshape(data.shape, shape))


@op("reshape_like")
def reshape_like(lhs, rhs):
    """Reference ``reshape_like``: reshape lhs to rhs's shape (sizes must
    match)."""
    return jnp.reshape(lhs, rhs.shape)


@op("unique", differentiable=False)
def unique(data):
    """Sorted distinct values.  Dynamic output shape — host-path op like
    ``boolean_mask`` (not jittable; inside jit use fixed-size masks)."""
    return jnp.unique(data)


@op("_onnx_expand")
def _onnx_expand(data, *, shape):
    """ONNX ``Expand`` semantics (the onnx2mx importer's target): the
    output shape is the NUMPY BROADCAST of input shape and ``shape`` —
    a 1 in ``shape`` keeps the input dim, unlike ``broadcast_to``."""
    import numpy as onp
    shape = tuple(int(s) for s in shape)
    # numpy broadcast rules — raises on incompatible dims, exactly as a
    # conforming ONNX runtime must
    out = onp.broadcast_shapes(tuple(data.shape), shape)
    full = (1,) * (len(out) - data.ndim) + tuple(data.shape)
    return jnp.broadcast_to(data.reshape(full), out)


def _mx_reshape(ishape, shape):
    """Support MXNet special codes: 0 (keep dim), -1 (infer), -2 (copy rest),
    -3 (merge two dims), -4 (split dim)."""
    if all(isinstance(s, int) and s > 0 or s == -1 for s in shape):
        return tuple(shape)
    out = []
    i = 0
    it = iter(range(len(shape)))
    k = 0
    shape = list(shape)
    while k < len(shape):
        s = shape[k]
        if s > 0:
            out.append(s)
            i += 1
        elif s == 0:
            out.append(ishape[i])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        elif s == -2:
            out.extend(ishape[i:])
            i = len(ishape)
        elif s == -3:
            out.append(ishape[i] * ishape[i + 1])
            i += 2
        elif s == -4:
            a, b = shape[k + 1], shape[k + 2]
            if a == -1:
                a = ishape[i] // b
            if b == -1:
                b = ishape[i] // a
            out.extend([a, b])
            i += 1
            k += 2
        else:
            raise MXNetError(f"bad reshape code {s}")
        k += 1
    return tuple(out)


alias("Reshape", "reshape")


@op("transpose")
def transpose(data, *, axes=None):
    return jnp.transpose(data, axes=axes if axes else None)


@op("expand_dims")
def expand_dims(data, *, axis):
    return jnp.expand_dims(data, axis)


@op("squeeze")
def squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis=axis)


@op("flatten")
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


alias("Flatten", "flatten")


@op("broadcast_to")
def broadcast_to(data, *, shape):
    tgt = tuple(o if s == 0 else s for s, o in zip(shape, data.shape)) \
        if len(shape) == data.ndim else tuple(shape)
    return jnp.broadcast_to(data, tgt)


@op("broadcast_axis")
def broadcast_axis(data, *, axis, size):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@op("swapaxes")
def swapaxes(data, *, dim1=0, dim2=1):
    return jnp.swapaxes(data, dim1, dim2)


alias("SwapAxis", "swapaxes")


@op("concat", variadic=True)
def concat(*data, dim=1):
    return jnp.concatenate(data, axis=dim)


alias("Concat", "concat")


@op("stack", variadic=True)
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@op("split")
def split(data, *, num_outputs, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


alias("SliceChannel", "split")


@op("slice")
def slice(data, *, begin, end, step=None):  # noqa: A001
    nd = data.ndim
    begin = tuple(begin) + (None,) * (nd - len(begin))
    end = tuple(end) + (None,) * (nd - len(end))
    step = (tuple(step) + (None,) * (nd - len(step))) if step else (None,) * nd
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@op("slice_axis")
def slice_axis(data, *, axis, begin, end):
    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@op("slice_like")
def slice_like(data, shape_like, *, axes=None):
    axes = axes or tuple(range(data.ndim))
    idx = [builtins.slice(None)] * data.ndim
    for a in axes:
        idx[a] = builtins.slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@op("tile")
def tile(data, *, reps):
    return jnp.tile(data, reps)


@op("repeat")
def repeat(data, *, repeats, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@op("flip")
def flip(data, *, axis):
    return jnp.flip(data, axis=axis)


alias("reverse", "flip")


@op("pad")
def pad(data, *, mode="constant", pad_width=(), constant_value=0):
    pw = list(zip(pad_width[::2], pad_width[1::2]))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode="constant",
                       constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


alias("Pad", "pad")


@op("diag")
def diag(data, *, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@op("depth_to_space")
def depth_to_space(data, *, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, b, b, c // (b * b), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (n, c // (b * b), h * b, w * b))


@op("space_to_depth")
def space_to_depth(data, *, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


# ======================================================================= #
# indexing
# ======================================================================= #

@op("take")
def take(a, indices, *, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


@op("pick")
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    idx = jnp.clip(idx, 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, idx, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@op("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@op("scatter_nd")
def scatter_nd(data, indices, *, shape):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(shape, data.dtype)
    return out.at[idx].add(data)


@op("one_hot", differentiable=False)
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on_value - off_value) + off_value).astype(jnp.dtype(dtype))


@op("boolean_mask")
def boolean_mask(data, index, *, axis=0):
    # dynamic shape: materialize on host path only (documented XLA limit);
    # inside jit use where/compress patterns instead
    mask = index.astype(bool)
    return jnp.compress(mask, data, axis=axis)


@op("sequence_mask")
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    # data: (seq, batch, ...) if axis==0 else (batch, seq, ...)
    L = data.shape[axis]
    pos = jnp.arange(L)
    if axis == 0:
        pos = pos.reshape((L,) + (1,) * (data.ndim - 1))
        sl = sequence_length.reshape((1, -1) + (1,) * (data.ndim - 2))
    else:
        pos = pos.reshape((1, L) + (1,) * (data.ndim - 2))
        sl = sequence_length.reshape((-1, 1) + (1,) * (data.ndim - 2))
    return jnp.where(pos < sl, data, jnp.asarray(value, data.dtype))


alias("SequenceMask", "sequence_mask")


@op("sequence_last")
def sequence_last(data, sequence_length=None, *, use_sequence_length=False,
                  axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [builtins.slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        batch = jnp.arange(data.shape[1])
        return data[last, batch]
    batch = jnp.arange(data.shape[0])
    return data[batch, last]


alias("SequenceLast", "sequence_last")


@op("sequence_reverse")
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    # reverse only the first sequence_length elements along axis 0
    L = data.shape[0]
    pos = jnp.arange(L).reshape((L,) + (1,) * (data.ndim - 1))
    sl = sequence_length.astype(jnp.int32).reshape(
        (1, -1) + (1,) * (data.ndim - 2))
    src = jnp.where(pos < sl, sl - 1 - pos, pos)
    return jnp.take_along_axis(data, jnp.broadcast_to(src.astype(jnp.int32),
                                                      data.shape), axis=0)


alias("SequenceReverse", "sequence_reverse")


# __getitem__ support: static parts of the key are closed over; advanced
# (array) indices are passed as primals so gradients flow through gathers.
_INDEX_SENTINEL = "__arr__"


def _index(data, key):
    import jax as _jax
    from ..ndarray.ndarray import NDArray

    arrays = []
    def strip(k):
        if isinstance(k, (_jax.Array, jnp.ndarray)) or hasattr(k, "aval"):
            arrays.append(k)
            return (_INDEX_SENTINEL, len(arrays) - 1)
        if isinstance(k, tuple):
            return tuple(strip(x) for x in k)
        return k
    skey = strip(key)

    def fill(k, arrs):
        if isinstance(k, tuple):
            if len(k) == 2 and k[0] == _INDEX_SENTINEL:
                return arrs[k[1]]
            return tuple(fill(x, arrs) for x in k)
        return k

    def impl(d, *idx_arrays):
        return d[fill(skey, idx_arrays)]

    from .registry import Op
    tmp = Op(name="_index", fn=impl)
    return invoke(tmp, [data] + arrays, {})


# ======================================================================= #
# creation ops (no tensor inputs -> plain functions, not @op)
# ======================================================================= #

def _ctx_put(arr, ctx):
    from ..ndarray.ndarray import NDArray
    if ctx is not None:
        arr = jax.device_put(arr, ctx.jax_device())
    return NDArray(arr, ctx)


def zeros(shape, ctx=None, dtype="float32"):
    return _ctx_put(jnp.zeros(shape, jnp.dtype(dtype or "float32")), ctx)


def ones(shape, ctx=None, dtype="float32"):
    return _ctx_put(jnp.ones(shape, jnp.dtype(dtype or "float32")), ctx)


def full(shape, val, ctx=None, dtype="float32"):
    return _ctx_put(jnp.full(shape, val, jnp.dtype(dtype or "float32")), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = jnp.arange(start, stop, step, jnp.dtype(dtype or "float32"))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return _ctx_put(out, ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return _ctx_put(jnp.linspace(start, stop, num, endpoint=endpoint,
                                 dtype=jnp.dtype(dtype or "float32")), ctx)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return _ctx_put(jnp.eye(N, M or N, k=k, dtype=jnp.dtype(dtype or "float32")),
                    ctx)


@op("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@op("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@op("full_like")
def full_like(data, *, fill_value=0.0):
    return jnp.full_like(data, fill_value)


# ----------------------------------------------------------------------- #
# AMP support ops (reference anchors ``all_finite`` / ``multi_all_finite``
# in src/operator/contrib — the overflow probes the dynamic LossScaler uses)
# ----------------------------------------------------------------------- #

@op("all_finite", differentiable=False)
def all_finite(data, *, init_output=True):
    return jnp.isfinite(data).all().astype(jnp.float32).reshape(1)


@op("multi_all_finite", differentiable=False, variadic=True)
def multi_all_finite(*arrays, num_arrays=0, init_output=True):
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.astype(jnp.float32).reshape(1)


@op("amp_cast")
def amp_cast(data, *, dtype="float16"):
    return data.astype(jnp.dtype(dtype))


@op("amp_multicast", differentiable=True, variadic=True)
def amp_multicast(*arrays, num_outputs=0, cast_narrow=False):
    """Cast all inputs to the widest (or narrowest) common float dtype."""
    dtypes = [a.dtype for a in arrays]
    pick = _min if cast_narrow else _max
    target = pick(dtypes, key=lambda d: jnp.finfo(d).bits
                  if jnp.issubdtype(d, jnp.floating) else 0)
    return tuple(a.astype(target) for a in arrays)
