"""Fused optimizer update ops (reference ``src/operator/optimizer_op.cc``:
``sgd_update``, ``adam_update``, ``lamb_update_phase1/2``, ``multi_sgd_*``,
``mp_*`` multi-precision variants — SURVEY.md §3.1 "optimizer_op" row).

TPU-native delta: the reference mutates ``weight``/state in place; here
every op is PURE — it returns the updated tensors (single output ops
support ``out=weight`` for reference-style call sites).  The Python
optimizers (``mxnet_tpu/optimizer``) fuse these formulas into the jitted
train step; these registered ops exist for ``mx.nd.*_update`` API parity
and for custom training loops.

All ops apply ``rescale_grad`` then ``clip_gradient`` (when >= 0) to the
incoming gradient, matching the reference order.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op

__all__ = [
    "sgd_update", "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update",
    "nag_mom_update", "mp_nag_mom_update", "adam_update", "adamw_update",
    "mp_adamw_update", "lamb_update_phase1", "lamb_update_phase2",
    "ftrl_update", "ftml_update", "rmsprop_update", "rmspropalex_update",
    "signsgd_update", "signum_update", "adagrad_update", "adadelta_update",
    "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
    "multi_mp_sgd_mom_update",
]


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@op("sgd_update", differentiable=False)
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@op("sgd_mom_update", differentiable=False)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight)
    return weight + mom_new, mom_new


@op("mp_sgd_update", differentiable=False)
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@op("mp_sgd_mom_update", differentiable=False)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@op("nag_mom_update", differentiable=False)
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


@op("mp_nag_mom_update", differentiable=False)
def mp_nag_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient) \
        + wd * weight32
    mom_new = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * mom_new)
    return w32.astype(weight.dtype), mom_new, w32


@op("adam_update", differentiable=False)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """Reference ``adam_update``: bias correction is folded into ``lr`` by
    the Python optimizer (as in the reference), not done in-op."""
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    w = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w, mean_new, var_new


@op("adamw_update", differentiable=False)
def adamw_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    """Reference ``_contrib_adamw_update``: decoupled weight decay; ``eta``
    is the schedule multiplier applied on top of ``lr``."""
    g = _prep(grad, rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    w = weight - eta * (lr * mean_new / (jnp.sqrt(var_new) + epsilon)
                        + lr * wd * weight)
    return w, mean_new, var_new


@op("mp_adamw_update", differentiable=False)
def mp_adamw_update(weight, grad, mean, var, weight32, *, lr, beta1=0.9,
                    beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    w32 = weight32 - eta * (lr * mean_new / (jnp.sqrt(var_new) + epsilon)
                            + lr * wd * weight32)
    return w32.astype(weight.dtype), mean_new, var_new, w32


@op("lamb_update_phase1", differentiable=False)
def lamb_update_phase1(weight, grad, mean, var, *, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """Phase 1: the raw LAMB direction g' (reference
    ``lamb_update_phase1``); phase 2 applies the layerwise trust ratio."""
    g = _prep(grad, rescale_grad, clip_gradient)
    mean_new = beta1 * mean + (1.0 - beta1) * g
    var_new = beta2 * var + (1.0 - beta2) * g * g
    m_hat, v_hat = mean_new, var_new
    if bias_correction:
        m_hat = mean_new / (1.0 - beta1 ** t)
        v_hat = var_new / (1.0 - beta2 ** t)
    direction = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * weight
    return direction, mean_new, var_new


@op("lamb_update_phase2", differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, *, lr, lower_bound=-1.0,
                       upper_bound=-1.0):
    """Phase 2: w -= lr * (r1/r2) * g with the trust ratio from the norms
    computed between phases (reference ``lamb_update_phase2``)."""
    if lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


@op("ftrl_update", differentiable=False)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    n_new = n + g * g
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) <= lamda1, 0.0,
        -(z_new - jnp.sign(z_new) * lamda1) /
        ((beta + jnp.sqrt(n_new)) / lr + wd))
    return w.astype(weight.dtype), z_new, n_new


@op("ftml_update", differentiable=False)
def ftml_update(weight, grad, d, v, z, *, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    g = _prep(grad, rescale_grad, clip_grad) + wd * weight
    v_new = beta2 * v + (1.0 - beta2) * g * g
    d_new = (1.0 - beta1 ** t) / lr * (
        jnp.sqrt(v_new / (1.0 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1.0 - beta1) * g - sigma * weight
    return -z_new / d_new, d_new, v_new, z_new


@op("rmsprop_update", differentiable=False)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    n_new = gamma1 * n + (1.0 - gamma1) * g * g
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


@op("rmspropalex_update", differentiable=False)
def rmspropalex_update(weight, grad, n, g_state, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Centered RMSProp (Graves 2013), reference ``rmspropalex_update``."""
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    n_new = gamma1 * n + (1.0 - gamma1) * g * g
    g_new = gamma1 * g_state + (1.0 - gamma1) * g
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(
        n_new - g_new * g_new + epsilon)
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_new, delta_new


@op("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@op("signum_update", differentiable=False)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    w = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w, mom_new


@op("adagrad_update", differentiable=False)
def adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    hist_new = history + g * g
    return (weight - lr * (g / (jnp.sqrt(hist_new) + epsilon)
                           + wd * weight), hist_new)


@op("adadelta_update", differentiable=False)
def adadelta_update(weight, grad, acc_g, acc_delta, *, rho=0.9,
                    epsilon=1e-5, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    acc_g_new = rho * acc_g + (1.0 - rho) * g * g
    delta = jnp.sqrt(acc_delta + epsilon) / \
        jnp.sqrt(acc_g_new + epsilon) * g
    acc_delta_new = rho * acc_delta + (1.0 - rho) * delta * delta
    return weight - delta, acc_g_new, acc_delta_new


# --------------------------------------------------------------------------- #
# fused multi-tensor updates: one op over interleaved tensor lists
# (reference ``multi_sgd_update`` family — the aggregated fast path driven
# by Optimizer.aggregate_num; on TPU one jit already fuses everything, so
# these exist for API parity and custom loops)
# --------------------------------------------------------------------------- #

@op("multi_sgd_update", differentiable=False, variadic=True)
def multi_sgd_update(*arrays, lrs, wds, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=None):
    """arrays = [w0, g0, w1, g1, ...]; returns the updated weights."""
    n = num_weights if num_weights is not None else len(arrays) // 2
    outs = []
    for i in range(n):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        g = _prep(g, rescale_grad, clip_gradient)
        outs.append(w - lrs[i] * (g + wds[i] * w))
    return tuple(outs)


@op("multi_sgd_mom_update", differentiable=False, variadic=True)
def multi_sgd_mom_update(*arrays, lrs, wds, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=None):
    """arrays = [w0, g0, m0, w1, g1, m1, ...] -> (w0', m0', w1', m1', ...)"""
    n = num_weights if num_weights is not None else len(arrays) // 3
    outs = []
    for i in range(n):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        g = _prep(g, rescale_grad, clip_gradient)
        m_new = momentum * m - lrs[i] * (g + wds[i] * w)
        outs += [w + m_new, m_new]
    return tuple(outs)


@op("multi_mp_sgd_update", differentiable=False, variadic=True)
def multi_mp_sgd_update(*arrays, lrs, wds, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=None):
    """arrays = [w0, g0, w32_0, ...] -> (w0', w32_0', ...)"""
    n = num_weights if num_weights is not None else len(arrays) // 3
    outs = []
    for i in range(n):
        w, g, w32 = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        g = _prep(g.astype(jnp.float32), rescale_grad, clip_gradient)
        w32_new = w32 - lrs[i] * (g + wds[i] * w32)
        outs += [w32_new.astype(w.dtype), w32_new]
    return tuple(outs)


@op("multi_mp_sgd_mom_update", differentiable=False, variadic=True)
def multi_mp_sgd_mom_update(*arrays, lrs, wds, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=None):
    """arrays = [w0, g0, m0, w32_0, ...] -> (w0', m0', w32_0', ...)"""
    n = num_weights if num_weights is not None else len(arrays) // 4
    outs = []
    for i in range(n):
        w, g, m, w32 = arrays[4 * i:4 * i + 4]
        g = _prep(g.astype(jnp.float32), rescale_grad, clip_gradient)
        m_new = momentum * m - lrs[i] * (g + wds[i] * w32)
        w32_new = w32 + m_new
        outs += [w32_new.astype(w.dtype), m_new, w32_new]
    return tuple(outs)
