"""Fused RNN op: the whole multi-layer (bi)directional recurrence as ONE
op — a ``lax.scan`` over time per layer/direction, compiled by XLA.

Reference counterpart: the fused ``RNN`` operator
(``src/operator/nn/rnn*``, SURVEY.md §3.1 "Operator corpus" nn family:
"fused RNN op [cuDNN LSTM/GRU + native CPU]").  Gate orders follow the
reference: LSTM gates (i, f, g, o) — so ``LSTMBias``'s forget chunk is
[H:2H] — and GRU gates (r, z, n) with the reference's
``n = tanh(i2h_n + r * h2h_n)`` formulation.

Weight layout per (layer, direction), matching the layer's parameter
order: i2h_weight (G·H, in), h2h_weight (G·H, H), i2h_bias, h2h_bias.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

__all__ = ["fused_rnn"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _rnn_step(mode, x_t, h, c, wi, wh, bi, bh):
    """One time step.  x_t (N, in), h/c (N, H).  Returns (out, h, c)."""
    gx = x_t @ wi.T + bi
    gh = h @ wh.T + bh
    H = h.shape[-1]
    if mode == "rnn_relu":
        h = jax.nn.relu(gx + gh)
        return h, h, c
    if mode == "rnn_tanh":
        h = jnp.tanh(gx + gh)
        return h, h, c
    if mode == "lstm":
        g = gx + gh
        i = jax.nn.sigmoid(g[:, :H])
        f = jax.nn.sigmoid(g[:, H:2 * H])
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:])
        c = f * c + i * gg
        h = o * jnp.tanh(c)
        return h, h, c
    if mode == "gru":
        r = jax.nn.sigmoid(gx[:, :H] + gh[:, :H])
        z = jax.nn.sigmoid(gx[:, H:2 * H] + gh[:, H:2 * H])
        n = jnp.tanh(gx[:, 2 * H:] + r * gh[:, 2 * H:])
        h = (1 - z) * n + z * h
        return h, h, c
    raise ValueError(f"unknown rnn mode {mode}")


def _scan_direction(mode, x, h0, c0, wi, wh, bi, bh, reverse):
    """x (T, N, in) → (out (T, N, H), h_n, c_n)."""

    def step(carry, x_t):
        h, c = carry
        out, h, c = _rnn_step(mode, x_t, h, c, wi, wh, bi, bh)
        return (h, c), out

    (h_n, c_n), out = lax.scan(step, (h0, c0), x, reverse=reverse)
    return out, h_n, c_n


@op("fused_rnn", variadic=True)
def fused_rnn(*arrays, mode="lstm", num_layers=1, bidirectional=False,
              dropout=0.0, training=False, layout="TNC"):
    """arrays = [x, h0, (c0 if lstm), then per (layer, direction):
    i2h_weight, h2h_weight, i2h_bias, h2h_bias].

    x is (T, N, in) for layout TNC or (N, T, in) for NTC; h0/c0 are
    (num_layers·dirs, N, H).  Returns (out, h_n[, c_n])."""
    ndir = 2 if bidirectional else 1
    x = arrays[0]
    if layout == "NTC":
        x = jnp.swapaxes(x, 0, 1)
    has_c = mode == "lstm"
    h0 = arrays[1]
    c0 = arrays[2] if has_c else None
    weights = arrays[3 if has_c else 2:]
    assert len(weights) == 4 * num_layers * ndir, (
        f"expected {4 * num_layers * ndir} weight arrays, got "
        f"{len(weights)}")

    inp = x
    h_states, c_states = [], []
    for l in range(num_layers):
        outs = []
        for d in range(ndir):
            idx = l * ndir + d
            wi, wh, bi, bh = weights[4 * idx:4 * idx + 4]
            h_init = h0[idx]
            c_init = c0[idx] if has_c else jnp.zeros_like(h_init)
            out, h_n, c_n = _scan_direction(
                mode, inp, h_init, c_init, wi, wh, bi, bh,
                reverse=(d == 1))
            outs.append(out)
            h_states.append(h_n)
            c_states.append(c_n)
        inp = outs[0] if ndir == 1 else jnp.concatenate(outs, axis=-1)
        if dropout and training and l < num_layers - 1:
            from .. import random as mxrandom
            keep = jax.random.bernoulli(mxrandom.next_key(), 1 - dropout,
                                        inp.shape)
            inp = jnp.where(keep, inp / (1 - dropout), 0).astype(inp.dtype)

    out = inp if layout == "TNC" else jnp.swapaxes(inp, 0, 1)
    h_n = jnp.stack(h_states)
    if has_c:
        return out, h_n, jnp.stack(c_states)
    return out, h_n
