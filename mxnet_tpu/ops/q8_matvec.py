"""Weight-only int8 matvec Pallas kernel (weight-streaming decode).

Batch-1 autoregressive decode is HBM-bound on the weight stream
(BASELINE.md decode roofline): every emitted token reads every matmul
weight once, so bytes-per-weight sets the latency floor.  Per-channel
int8 halves the bytes vs bf16 — but the plain XLA lowering of
``x @ wq.astype(bf16).T`` materializes the dequantized matrix in HBM
every step (measured 8x SLOWER than bf16).  The convert must happen in
VMEM: this kernel streams int8 weight tiles, converts in-register on the
VPU, and runs the MXU dot with f32 accumulation.

Used by ``kv_generate(weights='int8')`` (models/decoding.py).  Reference
counterpart: the int8 inference path of the reference's quantization
subsystem (SURVEY.md §3.2 quantization row) — redesigned TPU-side as a
serving-decode kernel rather than a calibrated conv/FC graph pass
(which lives in contrib/quantization.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# shared Pallas gating (one source of truth for the interpret/backend
# convention — see ops/attention.py)
from .attention import _interpret, _pallas_backend_ok as _on_tpu

__all__ = ["q8_matvec"]


def _kernel(x_ref, w_ref, out_ref):
    # int8 -> f32 conversion happens IN VMEM on the VPU (this Mosaic
    # toolchain rejects bf16 matmul operands — same convention as the
    # flash kernel); HBM only ever sees the int8 codes.  K is the inner
    # (fastest-varying) grid dim, so the same out block is revisited
    # consecutively and accumulates across K tiles in f32.
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    out_ref[:] += jnp.dot(x, w, preferred_element_type=jnp.float32)


# VMEM working-set budget per grid step (v5e has 16 MiB more/core; leave
# headroom for Mosaic's double-buffered pipeline copies)
_VMEM_BUDGET = 6 * 1024 * 1024


def _tile_bytes(B: int, bk: int, bo: int) -> int:
    # int8 codes tile + its f32 in-register convert, x slice, out block
    return bk * bo * (1 + 4) + B * bk * 4 + B * bo * 4


def _pick_tiles(B: int, K: int, O: int, limit: int = 2048):
    """(bk, bo) tile sizes: bo divides O and is a multiple of 128 (the
    lane tile — O is the minor dim of the (K, O) codes); bk divides K and
    is a multiple of 32 (the int8 sublane tile); together the working set
    fits the VMEM budget.  Prefers the largest admissible bo (big lane
    tiles keep the MXU fed), then the largest K tile that still fits —
    K-tiled accumulation when the full K cannot.  Returns (0, 0) if no
    admissible tiling exists (caller falls back to einsum)."""
    k_divs = [d for d in range(32, K + 1, 32) if K % d == 0]
    for bo in range(min(O, limit), 0, -128):
        if O % bo or bo % 128:
            continue
        for bk in reversed(k_divs):
            if _tile_bytes(B, bk, bo) <= _VMEM_BUDGET:
                return bk, bo
    return 0, 0


def q8_matvec(x, wt, s, bias=None):
    """``(x @ wt) * s + bias`` with int8 weights streamed from HBM.

    - ``x`` (B, K) float (bf16/f32) — B is the decode batch, small;
    - ``wt`` (K, O) int8 codes, PRE-TRANSPOSED at quantization time so
      the kernel runs the canonical (B,K)x(K,O) Mosaic matmul (a
      transpose inside the kernel would relayout every tile);
    - ``s`` (O,) f32 per-output-channel scales; ``bias`` (O,) optional.

    Returns (B, O) float32.  Falls back to the XLA einsum off-TPU or for
    shapes the kernel can't tile (K not sublane-aligned).
    """
    B, K = x.shape
    O = wt.shape[1]
    bk, bo = _pick_tiles(B, K, O)
    if not _on_tpu() or not bo:
        y = jnp.einsum("bi,io->bo", x, wt.astype(x.dtype),
                       preferred_element_type=jnp.float32)
    else:
        y = pl.pallas_call(
            _kernel,
            grid=(O // bo, K // bk),
            in_specs=[pl.BlockSpec((B, bk), lambda o, k: (0, k)),
                      pl.BlockSpec((bk, bo), lambda o, k: (k, o))],
            out_specs=pl.BlockSpec((B, bo), lambda o, k: (0, o)),
            out_shape=jax.ShapeDtypeStruct((B, O), jnp.float32),
            interpret=_interpret(),
        )(x, wt)
    y = y * s
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y
