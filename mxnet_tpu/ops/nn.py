"""Neural-network operators.

Reference surface: ``src/operator/nn/**`` (SURVEY.md §3.1 "Operator corpus"
nn/ family: Convolution + cuDNN autotuned paths, FullyConnected, BatchNorm,
LayerNorm, Pooling, Activation, Softmax, Dropout, Embedding, ...).

TPU-native: every op lowers to XLA HLO that tiles onto the MXU
(``lax.conv_general_dilated``, ``jnp.matmul``) or fuses into neighbors
(norms, activations).  There is no autotune knob — XLA picks conv
algorithms — and no cuDNN analog to manage.  Layouts follow the reference
(NCHW default) but every conv/pool accepts ``layout=NHWC`` which is
preferred on TPU.
"""
from __future__ import annotations

import builtins
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import op, alias


# ----------------------------------------------------------------------- #
# activations
# ----------------------------------------------------------------------- #

@op("Activation")
def Activation(data, *, act_type="relu"):
    fns = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "log_sigmoid": jax.nn.log_sigmoid,
        "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
        "gelu": jax.nn.gelu,
        "erf_gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "swish": jax.nn.silu,
    }
    if act_type not in fns:
        raise MXNetError(f"unknown act_type {act_type}")
    return fns[act_type](data)


@op("LeakyReLU")
def LeakyReLU(data, gamma=None, *, act_type="leaky", slope=0.25,
              lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and data.ndim > 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":  # eval mode: use mean slope
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise MXNetError(f"unknown LeakyReLU act_type {act_type}")


@op("softmax")
def softmax(data, length=None, *, axis=-1, temperature=None,
            use_length=False):
    x = data / temperature if temperature else data
    if use_length and length is not None:
        L = data.shape[axis]
        pos = jnp.arange(L)
        shape = [1] * data.ndim
        shape[axis] = L
        pos = pos.reshape(shape)
        ln = length.reshape(length.shape + (1,) * (data.ndim - length.ndim))
        ln = jnp.moveaxis(ln, -1, axis) if axis != -1 and axis != data.ndim - 1 else ln
        mask = pos < ln
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax")
def log_softmax(data, *, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    if x.dtype in (jnp.float16, jnp.bfloat16):
        # fp32 logits math, half-precision output (mixed-precision softmax)
        return jax.nn.log_softmax(x.astype(jnp.float32),
                                  axis=axis).astype(data.dtype)
    return jax.nn.log_softmax(x, axis=axis)


@op("_sparse_softmax_ce")
def _sparse_softmax_ce(pred, label, *, axis=-1):
    """Fused sparse-label softmax cross-entropy: per-element
    ``lse(pred) - pred[label]`` with keepdims on the class axis.

    The f32 math happens INSIDE the reductions (max + sum-of-exp chains
    XLA fuses into loop fusions), so no (N, V) f32 logits array is ever
    materialized — on the BERT MLM head that materialized convert alone
    was 1.5 ms/step (3% of the step).  The autodiff backward is
    ``softmax - onehot`` recomputed elementwise from the bf16 logits."""
    ax = axis % pred.ndim
    m = jnp.max(pred, axis=ax, keepdims=True)
    z = jnp.exp(pred.astype(jnp.float32) - m.astype(jnp.float32))
    lse = m.astype(jnp.float32) + jnp.log(
        jnp.sum(z, axis=ax, keepdims=True))
    lab = jnp.expand_dims(label.astype(jnp.int32), ax) \
        if label.ndim == pred.ndim - 1 else label.astype(jnp.int32)
    # clamp like the pick path (mxnet 'clip' mode): ignore/pad labels
    # outside [0, V) must not produce NaN/wrapped gathers
    lab = jnp.clip(lab, 0, pred.shape[ax] - 1)
    picked = jnp.take_along_axis(pred, lab, axis=ax).astype(jnp.float32)
    return (lse - picked).astype(pred.dtype)


@op("softmin")
def softmin(data, *, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@op("SoftmaxActivation")
def SoftmaxActivation(data, *, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(
        data.shape)


# ----------------------------------------------------------------------- #
# dense / conv / pooling
# ----------------------------------------------------------------------- #

@op("FullyConnected")
def FullyConnected(data, weight, bias=None, *, num_hidden=0, no_bias=False,
                   flatten=True):
    """Reference anchor ``FullyConnected``: y = x W^T + b.  The matmul is
    the MXU hot path; keep inputs bf16-friendly and batched."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    y = jnp.matmul(x, weight.T)
    if not no_bias and bias is not None:
        y = y + bias
    return y


def _pair(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v + (v[-1],) * (n - len(v)) if len(v) < n else v


@op("Convolution")
def Convolution(data, weight, bias=None, *, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                layout=None, cudnn_tune=None, cudnn_off=False,
                workspace=1024):
    """Reference anchor ``Convolution`` (+ ``nn/cudnn/`` autotuned paths).
    Lowers to one ``lax.conv_general_dilated`` — XLA chooses the algorithm
    (cudnn_tune/workspace accepted for API compat, ignored)."""
    ndim = len(kernel)
    stride = _pair(stride or 1, ndim)
    dilate = _pair(dilate or 1, ndim)
    pad = _pair(pad or 0, ndim)
    spatial = "DHW"[-ndim:]
    if layout is None or layout.startswith("NC"):
        dn_in = "NC" + spatial
        dn_ker = "OI" + spatial
        dn_out = "NC" + spatial
        feat_axis = 1
    else:  # NHWC-style (TPU-preferred)
        dn_in = "N" + spatial + "C"
        # weights stay OIHW in EVERY layout so parameters (and .params
        # checkpoints) are layout-invariant; XLA relayouts the small
        # kernel tensor internally
        dn_ker = "OI" + spatial
        dn_out = "N" + spatial + "C"
        feat_axis = data.ndim - 1
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (dn_in, dn_ker, dn_out))
    if feat_axis == data.ndim - 1 and ndim == 2 and \
            all(p == 0 for p in pad):
        # NHWC 1x1 stride-1: route through the fused Pallas backward
        # (dgrad+wgrad in one HBM pass — BASELINE.md ResNet section;
        # the gate re-checks shape/stride/groups and falls back here)
        from .conv_fused import conv1x1_nhwc, fused_bwd_supported
        if fused_bwd_supported(data.shape, weight.shape, stride, dilate,
                               num_group,
                               itemsize=jnp.dtype(data.dtype).itemsize):
            out = conv1x1_nhwc(data, weight)
            if not no_bias and bias is not None:
                out = out + bias.reshape((1,) * (out.ndim - 1) + (-1,))
            return out
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=None)
    if not no_bias and bias is not None:
        bshape = [1] * out.ndim
        bshape[feat_axis] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


@op("Deconvolution")
def Deconvolution(data, weight, bias=None, *, kernel=(), stride=(),
                  dilate=(), pad=(), adj=(), num_filter=0, num_group=1,
                  no_bias=True, layout=None, target_shape=None,
                  cudnn_tune=None, cudnn_off=False, workspace=512):
    ndim = len(kernel)
    stride = _pair(stride or 1, ndim)
    pad = _pair(pad or 0, ndim)
    dilate = _pair(dilate or 1, ndim)
    adj = _pair(adj or 0, ndim)
    spatial = "DHW"[-ndim:]
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape, ("NC" + spatial, "IO" + spatial,
                                   "NC" + spatial))
    pads = []
    for k, s, p, d, a in zip(kernel, stride, pad, dilate, adj):
        ke = (k - 1) * d + 1
        pads.append((ke - 1 - p, ke - 1 - p + a))
    out = lax.conv_general_dilated(
        data, weight, window_strides=(1,) * ndim, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


@op("Pooling")
def Pooling(data, *, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid",
            count_include_pad=True, layout=None, cudnn_off=False):
    ndim = len(kernel) if kernel else data.ndim - 2
    channels_last = layout is not None and layout[1] != "C"
    sp = tuple(range(2, 2 + ndim)) if not channels_last else \
        tuple(range(1, 1 + ndim))
    if global_pool:
        if pool_type == "max":
            return jnp.max(data, axis=sp, keepdims=True)
        return jnp.mean(data, axis=sp, keepdims=True)
    stride = _pair(stride or kernel, ndim)
    pad = _pair(pad or 0, ndim)
    if channels_last:
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = ((0, 0),) + tuple((p, p) for p in pad) + ((0, 0),)
    else:
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full":
        # ceil-mode: pad extra on the high side so the last window fits
        newpads = list(pads)
        off = 2 if not channels_last else 1
        for i in range(ndim):
            size = data.shape[off + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra = (stride[i] - rem) % stride[i] if rem else 0
            lo, hi = newpads[off + i]
            newpads[off + i] = (lo, hi + extra)
        pads = tuple(newpads)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        p = 2.0
        s = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add, window,
                              strides, pads)
        return s ** (1.0 / p)
    raise MXNetError(f"unknown pool_type {pool_type}")


# ----------------------------------------------------------------------- #
# normalization — multi-output ops return (out, mean, var) so the Gluon
# layer can commit moving stats functionally (SURVEY.md §7: no aux-state
# mutation inside traced code)
# ----------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _bn_stats_core(data, gamma, beta, moving_mean, moving_var, eps,
                   momentum, fix_gamma, use_global_stats, axis, training):
    return _bn_stats_fwd_math(data, gamma, beta, moving_mean, moving_var,
                              eps, momentum, fix_gamma, use_global_stats,
                              axis, training)


def _bn_stats_fwd(data, gamma, beta, moving_mean, moving_var, eps,
                  momentum, fix_gamma, use_global_stats, axis, training):
    outs = _bn_stats_fwd_math(data, gamma, beta, moving_mean, moving_var,
                              eps, momentum, fix_gamma, use_global_stats,
                              axis, training)
    # residuals: x, the (stop-gradient) batch stats, and the small param
    # vectors (their dtypes shape the cotangents — beta/moving stats may
    # differ from gamma's dtype under AMP)
    return outs, (data, gamma, beta, moving_mean, moving_var,
                  outs[3], outs[4])


def _bn_stats_bwd(eps, momentum, fix_gamma, use_global_stats, axis,
                  training, res, cts):
    """Hand-written BN backward (VERDICT r3 item 1 escalation): the
    autodiff of the shifted-stats forward materializes extra reduce +
    elementwise HBM passes; the closed form needs exactly TWO sibling
    reductions (Σdy, Σdy·x̂ — one fused pass over dy, x) plus one
    elementwise pass for dx:

        dβ = Σ dy;  dγ = Σ dy·x̂
        dx = (γ·inv)·(dy − (dβ + x̂·dγ)/n)      (batch stats)
        dx = (γ·inv)·dy                          (global stats)
    """
    data, gamma, beta, moving_mean, moving_var, mean, var = res
    g_out = cts[0]  # the other 4 outputs are stop_gradient'ed
    nd_ = data.ndim
    ax = axis % nd_
    red = tuple(i for i in range(nd_) if i != ax)
    bshape = [1] * nd_
    bshape[ax] = data.shape[ax]
    n = 1
    for i in red:
        n *= data.shape[i]
    x32 = data.astype(jnp.float32)
    g32 = g_out.astype(jnp.float32)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).reshape(bshape)
    xhat = (x32 - mean.astype(jnp.float32).reshape(bshape)) * inv
    dbeta = jnp.sum(g32, axis=red)
    dgamma = jnp.sum(g32 * xhat, axis=red)
    geff = 1.0 if fix_gamma else gamma.astype(jnp.float32).reshape(bshape)
    if training and not use_global_stats:
        dx = (geff * inv) * (
            g32 - (dbeta.reshape(bshape)
                   + xhat * dgamma.reshape(bshape)) / n)
    else:
        dx = (geff * inv) * g32
    return (dx.astype(data.dtype),
            jnp.zeros_like(gamma) if fix_gamma
            else dgamma.astype(gamma.dtype),
            dbeta.astype(beta.dtype),
            jnp.zeros_like(moving_mean), jnp.zeros_like(moving_var))


_bn_stats_core.defvjp(_bn_stats_fwd, _bn_stats_bwd)


@op("_BatchNormStats")
def _BatchNormStats(data, gamma, beta, moving_mean, moving_var, *, eps=1e-5,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    axis=1, training=True):
    """Internal: returns ``(out, new_moving_mean, new_moving_var, batch_mean,
    batch_var)``.  The Gluon layer commits the new moving stats functionally
    (no aux-state mutation inside traced code, SURVEY.md §7).  Backward is
    the hand-written two-pass closed form (``_bn_stats_bwd``), not
    autodiff of the shifted-stats forward."""
    return _bn_stats_core(data, gamma, beta, moving_mean, moving_var,
                          float(eps), float(momentum), bool(fix_gamma),
                          bool(use_global_stats), int(axis), bool(training))


def _bn_stats_fwd_math(data, gamma, beta, moving_mean, moving_var, eps,
                       momentum, fix_gamma, use_global_stats, axis,
                       training):
    red = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if training and not use_global_stats:
        # ONE-PASS stats: E[x-s] and E[(x-s)²] are sibling reductions over
        # the same read, which XLA fuses into a single HBM pass (vs
        # mean-then-var = two full passes — measured 2x BN-stat traffic on
        # the ResNet-50 step).  The per-channel shift s = moving_mean is
        # the standard shifted-data guard against E[x²]-E[x]² catastrophic
        # cancellation: after warm-up s tracks the true mean, so the
        # squared terms stay O(var) instead of O(mean²).  f32 accumulation
        # for bf16 inputs.
        x32 = data.astype(jnp.float32) if data.dtype in (
            jnp.float16, jnp.bfloat16) else data
        n = 1
        for i in red:
            n *= data.shape[i]
        shift = lax.stop_gradient(moving_mean).astype(
            jnp.float32).reshape(bshape)
        d = x32 - shift
        s1 = jnp.sum(d, axis=red) / n
        s2 = jnp.sum(d * d, axis=red) / n
        mean = (shift.reshape(-1) + s1).astype(moving_mean.dtype)
        var = jnp.maximum(s2 - s1 * s1, 0.0).astype(moving_var.dtype)
        new_mm = moving_mean * momentum + mean * (1 - momentum)
        new_mv = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * (inv * g).reshape(bshape) \
        + beta.reshape(bshape)
    return (out.astype(data.dtype),
            lax.stop_gradient(new_mm), lax.stop_gradient(new_mv),
            lax.stop_gradient(mean), lax.stop_gradient(var))


def BatchNorm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-5,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False, **_ignored):
    """Reference anchor ``BatchNorm`` — public surface: one output by
    default, ``(out, batch_mean, batch_var)`` with ``output_mean_var``.
    Training behavior follows ``autograd.is_training()`` like the
    reference."""
    from .. import autograd
    outs = _BatchNormStats(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats, axis=axis,
        training=autograd.is_training())
    out, _mm, _mv, mean, var = outs
    if output_mean_var:
        return out, mean, var
    return out


@op("LayerNorm")
def LayerNorm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    """Reference anchor ``LayerNorm`` (fused CUDA kernel there; XLA fuses
    the reduction+scale chain here).  Statistics always accumulate in fp32
    — bf16 inputs keep bf16 storage but fp32 numerics (TPU mixed-precision
    convention)."""
    x = data.astype(jnp.float32) if data.dtype in (jnp.float16,
                                                   jnp.bfloat16) else data
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = ((x - mean) * inv * gamma.astype(x.dtype).reshape(shape)
           + beta.astype(x.dtype).reshape(shape)).astype(data.dtype)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@op("InstanceNorm")
def InstanceNorm(data, gamma, beta, *, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) + \
        beta.reshape(shape)


@op("GroupNorm")
def GroupNorm(data, gamma, beta, *, num_groups=1, eps=1e-5):
    n, c = data.shape[0], data.shape[1]
    x = data.reshape((n, num_groups, c // num_groups) + data.shape[2:])
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@op("RMSNorm")
def RMSNorm(data, gamma, *, axis=-1, eps=1e-6):
    """TPU-native addition (no reference analog; used by Llama-family
    models).  f32 statistics + f32 gamma application for half-precision
    inputs, single downcast at the end (same mixed-precision convention
    as LayerNorm)."""
    x = data.astype(jnp.float32) if data.dtype in (jnp.float16,
                                                   jnp.bfloat16) else data
    ms = jnp.mean(jnp.square(x), axis=axis, keepdims=True)
    gshape = [1] * data.ndim
    gshape[axis] = data.shape[axis]
    return (x * lax.rsqrt(ms + eps)
            * gamma.astype(x.dtype).reshape(gshape)).astype(data.dtype)


# ----------------------------------------------------------------------- #
# dropout / embedding
# ----------------------------------------------------------------------- #

@op("_DropoutImpl")
def _DropoutImpl(data, key, *, p=0.5, axes=()):
    """Pure dropout given an explicit uint32 PRNG key (randomness must be an
    input to stay pure under jit)."""
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape)
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


def Dropout(data, key=None, *, p=0.5, mode="training", axes=(),
            cudnn_off=False, training=None):
    """Reference anchor ``Dropout`` (cudnn path there).  Applies in training
    mode (``autograd.is_training()``) or when ``mode='always'``; a fresh key
    is drawn from ``mxnet_tpu.random`` unless one is threaded explicitly
    (hybridize does that)."""
    from .. import autograd, random as mxrandom
    if training is None:
        training = autograd.is_training()
    if (not training and mode != "always") or p <= 0.0:
        return data
    if key is None:
        key = mxrandom.next_key()
    return _DropoutImpl(data, key, p=p, axes=tuple(axes))


@op("Embedding")
def Embedding(data, weight, *, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """Reference anchor ``Embedding``: gather rows.  On TPU this is a
    ``take`` that XLA lowers to a dynamic-gather; sharded tables come from
    GSPMD annotations (SURVEY.md §3.3 sparse/EP row)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ----------------------------------------------------------------------- #
# losses shipped as ops in the reference
# ----------------------------------------------------------------------- #

@op("SoftmaxOutput")
def SoftmaxOutput(data, label, *, grad_scale=1.0, ignore_label=-1,
                  multi_output=False, use_ignore=False, preserve_shape=False,
                  normalization="null", out_grad=False, smooth_alpha=0.0):
    """Reference anchor ``SoftmaxOutput``: forward = softmax; BACKWARD is the
    cross-entropy gradient ``(p - onehot(label)) * grad_scale`` regardless of
    the incoming cotangent (unless ``out_grad``) — the semantics the legacy
    Module training loop relies on (backward with implicit ones).

    ``multi_output=True`` softmaxes over the channel axis (axis 1) of
    ``(n, c, d1...)`` inputs with ``(n, d1...)`` labels, matching the
    reference's NCHW segmentation-style usage."""
    axis = 1 if (multi_output and data.ndim > 2) else -1

    @jax.custom_vjp
    def f(d, l):
        return jax.nn.softmax(d, axis=axis)

    def fwd(d, l):
        return jax.nn.softmax(d, axis=axis), (d, l)

    def bwd(res, g):
        d, l = res
        dm = jnp.moveaxis(d, axis, -1) if axis != -1 else d
        p = jax.nn.softmax(dm, axis=-1)
        v = dm.shape[-1]
        if l.shape == d.shape:  # distribution labels
            lm = jnp.moveaxis(l, axis, -1) if axis != -1 else l
            onehot = lm.astype(d.dtype)
            l_is_dist = True
        else:
            onehot = jax.nn.one_hot(l.astype(jnp.int32), v, dtype=d.dtype)
            l_is_dist = False
        if smooth_alpha:
            onehot = onehot * (1.0 - smooth_alpha) + smooth_alpha / v
        grad = p - onehot
        scale = grad_scale
        if use_ignore and not l_is_dist:
            mask = (l.astype(jnp.int32) != int(ignore_label))
            grad = grad * mask[..., None].astype(d.dtype)
            if normalization == "valid":
                scale = scale / jnp.maximum(mask.sum(), 1).astype(d.dtype)
        if normalization == "batch":
            scale = scale / d.shape[0]
        grad = grad * scale
        if out_grad:
            gm = jnp.moveaxis(g, axis, -1) if axis != -1 else g
            grad = grad * gm
        if axis != -1:
            grad = jnp.moveaxis(grad, -1, axis)
        return grad.astype(d.dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@op("CTCLoss")
def CTCLoss(data, label, data_lengths=None, label_lengths=None, *,
            use_data_lengths=False, use_label_lengths=False,
            blank_label="first"):
    """CTC via the standard alpha recursion in log space with lax.scan
    (reference: warp-ctc / native kernel).  data: (T, B, V) logits."""
    T, B, V = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else V - 1
    lab = label.astype(jnp.int32)
    Lmax = lab.shape[1]
    if label_lengths is not None and use_label_lengths:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        # count non-(-1|0) entries per reference convention (-1 padding)
        lab_len = jnp.sum((lab >= 0) & (lab != -1), axis=1).astype(jnp.int32)
        lab_len = jnp.where(lab_len == 0, Lmax, lab_len)
    if data_lengths is not None and use_data_lengths:
        t_len = data_lengths.astype(jnp.int32)
    else:
        t_len = jnp.full((B,), T, jnp.int32)

    S = 2 * Lmax + 1
    # extended label seq: blank, l1, blank, l2, ... blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(lab == -1, blank, lab))
    neg_inf = -1e30

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
    first_lab = ext[:, 1]
    alpha0 = alpha0.at[:, 1].set(logp[0, jnp.arange(B), first_lab])

    def lse(a, b):
        m = jnp.maximum(a, b)
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        return jnp.where((a <= neg_inf) & (b <= neg_inf), neg_inf,
                         m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m)))

    same = jnp.concatenate(
        [jnp.ones((B, 2), bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, t):
        shifted1 = jnp.concatenate([jnp.full((B, 1), neg_inf),
                                    alpha[:, :-1]], axis=1)
        shifted2 = jnp.concatenate([jnp.full((B, 2), neg_inf),
                                    alpha[:, :-2]], axis=1)
        a = lse(alpha, shifted1)
        a = jnp.where(same, a, lse(a, shifted2))
        emit = logp[t, jnp.arange(B)[:, None], ext]
        new = a + emit
        new = jnp.where((t < t_len)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = 2 * lab_len
    end2 = 2 * lab_len - 1
    br = jnp.arange(B)
    ll = lse(alpha[br, end1], alpha[br, jnp.maximum(end2, 0)])
    return -ll


@op("MakeLoss")
def MakeLoss(data, *, grad_scale=1.0, valid_thresh=0.0,
             normalization="null"):
    return data


alias("make_loss", "MakeLoss")


# ----------------------------------------------------------------------- #
# attention (reference: contrib interleaved matmul selfatt ops, BERT path)
# ----------------------------------------------------------------------- #

@op("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, *, heads=1):
    """(L, B, 3*E) interleaved qkv -> (B*heads, L, L) scores (reference
    anchor ``_contrib_interleaved_matmul_selfatt_qk``)."""
    L, B, E3 = queries_keys_values.shape
    E = E3 // 3
    x = queries_keys_values.reshape(L, B, heads, 3 * (E // heads))
    hd = E // heads
    q = x[..., :hd]
    k = x[..., hd:2 * hd]
    q = jnp.transpose(q, (1, 2, 0, 3)).reshape(B * heads, L, hd)
    k = jnp.transpose(k, (1, 2, 0, 3)).reshape(B * heads, L, hd)
    return jnp.matmul(q, jnp.swapaxes(k, -1, -2)) / jnp.sqrt(
        jnp.asarray(hd, q.dtype))


@op("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, *,
                                      heads=1):
    L, B, E3 = queries_keys_values.shape
    E = E3 // 3
    hd = E // heads
    x = queries_keys_values.reshape(L, B, heads, 3 * hd)
    v = x[..., 2 * hd:]
    v = jnp.transpose(v, (1, 2, 0, 3)).reshape(B * heads, L, hd)
    out = jnp.matmul(attention, v)  # (B*heads, L, hd)
    out = out.reshape(B, heads, L, hd)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(L, B, E)


# ----------------------------------------------------------------------- #
# vision ops: upsampling / resize / ROI / NMS / spatial sampling
# (reference src/operator/{nn,contrib}/ — SURVEY.md §3.1 operator corpus)
# ----------------------------------------------------------------------- #

@op("UpSampling")
def UpSampling(data, *, scale=2, sample_type="nearest", num_args=1):
    """Reference anchor ``UpSampling`` (NCHW).  nearest: repeat; bilinear:
    resize (the reference's bilinear path uses a Deconvolution with a fixed
    kernel — same result)."""
    n, c, h, w = data.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        return out
    return jax.image.resize(data, (n, c, h * scale, w * scale),
                            method="bilinear")


@op("_contrib_BilinearResize2D")
def BilinearResize2D(data, *, height=0, width=0, scale_height=None,
                     scale_width=None, mode="size",
                     align_corners=True):
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(round(h * scale_height))
        width = int(round(w * (scale_width or scale_height)))
    return jax.image.resize(data, (n, c, int(height), int(width)),
                            method="bilinear")


alias("BilinearResize2D", "_contrib_BilinearResize2D")


@op("_contrib_ROIAlign")
def ROIAlign(data, rois, *, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=2, position_sensitive=False, aligned=False):
    """Reference anchor ``_contrib_ROIAlign`` (RCNN head).  rois:
    (R, 5) [batch_idx, x1, y1, x2, y2] in image coords.  Bilinear sampling
    on a fixed grid — vectorized over ROIs/bins, MXU-free but fully fused
    by XLA."""
    n, c, h, w = data.shape
    ph, pw = pooled_size
    rois = rois.astype(jnp.float32)
    batch_idx = rois[:, 0].astype(jnp.int32)
    offset = 0.5 if aligned else 0.0
    x1 = rois[:, 1] * spatial_scale - offset
    y1 = rois[:, 2] * spatial_scale - offset
    x2 = rois[:, 3] * spatial_scale - offset
    y2 = rois[:, 4] * spatial_scale - offset
    roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    bin_h = roi_h / ph                                   # (R,)
    bin_w = roi_w / pw
    s = max(int(sample_ratio), 1)
    # sample grid: (ph*s) x (pw*s) points per ROI
    iy = (jnp.arange(ph * s) + 0.5) / s                  # in bin units
    ix = (jnp.arange(pw * s) + 0.5) / s
    ys = y1[:, None] + bin_h[:, None] * iy[None, :]      # (R, ph*s)
    xs = x1[:, None] + bin_w[:, None] * ix[None, :]      # (R, pw*s)

    def bilinear(img, yy, xx):
        """img: (c,h,w); yy: (ph*s,); xx: (pw*s,) → (c, ph*s, pw*s)."""
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = (yy - y0)[:, None]
        wx = (xx - x0)[None, :]
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                v10 * wy * (1 - wx) + v11 * wy * wx)

    def per_roi(b, yy, xx):
        img = data[b]                                    # (c,h,w)
        sampled = bilinear(img, yy, xx)                  # (c, ph*s, pw*s)
        pooled = sampled.reshape(c, ph, s, pw, s).mean(axis=(2, 4))
        return pooled

    return jax.vmap(per_roi)(batch_idx, ys, xs)          # (R, c, ph, pw)


alias("ROIAlign", "_contrib_ROIAlign")


@op("ROIPooling")
def ROIPooling(data, rois, *, pooled_size=(7, 7), spatial_scale=1.0):
    """Reference anchor ``ROIPooling`` (max-pool variant, Fast-RCNN)."""
    n, c, h, w = data.shape
    ph, pw = pooled_size
    rois = rois.astype(jnp.float32)
    batch_idx = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rois[:, 1] * spatial_scale).astype(jnp.int32)
    y1 = jnp.round(rois[:, 2] * spatial_scale).astype(jnp.int32)
    x2 = jnp.round(rois[:, 3] * spatial_scale).astype(jnp.int32)
    y2 = jnp.round(rois[:, 4] * spatial_scale).astype(jnp.int32)

    ys = jnp.arange(h)
    xs = jnp.arange(w)

    def per_roi(b, yy1, xx1, yy2, xx2):
        img = data[b]
        roi_h = jnp.maximum(yy2 - yy1 + 1, 1)
        roi_w = jnp.maximum(xx2 - xx1 + 1, 1)
        # bin index of every pixel, -1 outside the roi
        ybin = jnp.where((ys >= yy1) & (ys <= yy2),
                         ((ys - yy1) * ph) // roi_h, -1)
        xbin = jnp.where((xs >= xx1) & (xs <= xx2),
                         ((xs - xx1) * pw) // roi_w, -1)
        onehot_y = (ybin[None, :] == jnp.arange(ph)[:, None])  # (ph, h)
        onehot_x = (xbin[None, :] == jnp.arange(pw)[:, None])  # (pw, w)
        mask = onehot_y[:, None, :, None] & onehot_x[None, :, None, :]
        big = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = big.max(axis=(3, 4))                        # (c, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(per_roi)(batch_idx, y1, x1, y2, x2)


@op("_contrib_box_nms", differentiable=False)
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Reference anchor ``_contrib_box_nms`` (SSD/RCNN post-processing).
    data: (..., N, K) rows [id?, score, x1, y1, x2, y2, ...]; suppressed
    rows have score set to -1 (reference convention).  Static-shape NMS via
    a fori-loop over the score-sorted boxes."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])

    def one(batch):
        scores = batch[:, score_index]
        boxes = lax.dynamic_slice_in_dim(batch, coord_start, 4, axis=1)
        ids = batch[:, id_index] if id_index >= 0 else None
        order = jnp.argsort(-scores)
        n = scores.shape[0]
        keep_lim = n if topk < 0 else builtins.min(topk, n)

        x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
        area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)

        def iou(i, j):
            xx1 = jnp.maximum(x1[i], x1[j])
            yy1 = jnp.maximum(y1[i], y1[j])
            xx2 = jnp.minimum(x2[i], x2[j])
            yy2 = jnp.minimum(y2[i], y2[j])
            inter = jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0)
            return inter / jnp.maximum(area[i] + area[j] - inter, 1e-12)

        def body(k, suppressed):
            i = order[k]
            valid_i = jnp.logical_and(~suppressed[i],
                                      scores[i] >= valid_thresh)
            valid_i = jnp.logical_and(valid_i, k < keep_lim)
            others = order
            ious = jax.vmap(lambda j: iou(i, j))(others)
            same_class = jnp.ones_like(ious, bool) if (
                force_suppress or ids is None) else (ids[others] == ids[i])
            kill = (ious > overlap_thresh) & same_class & \
                (jnp.arange(n) > k)
            kill_idx = jnp.where(kill, others, i)
            new_sup = suppressed.at[kill_idx].set(
                jnp.where(kill, valid_i | suppressed[kill_idx],
                          suppressed[kill_idx]))
            return new_sup

        suppressed = lax.fori_loop(0, n, body,
                                   jnp.zeros(n, bool))
        # reference discards all non-topk candidates outright (score -1),
        # not just excludes them as suppressors
        rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n))
        new_scores = jnp.where(
            suppressed | (scores < valid_thresh) | (rank >= keep_lim),
            -1.0, scores)
        return batch.at[:, score_index].set(new_scores)

    return jax.vmap(one)(flat).reshape(shape)


alias("box_nms", "_contrib_box_nms")


@op("GridGenerator")
def GridGenerator(data, *, transform_type="affine", target_shape=(0, 0)):
    """Reference anchor ``GridGenerator``: affine (N,6) → sampling grid
    (N, 2, H, W) in [-1, 1] coords (pairs with BilinearSampler — the STN
    pipeline)."""
    h, w = target_shape
    theta = data.reshape(-1, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)                       # (h, w)
    ones = jnp.ones_like(gx)
    coords = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, h*w)
    out = jnp.einsum("nij,jk->nik", theta.astype(jnp.float32), coords)
    return out.reshape(-1, 2, h, w)


@op("BilinearSampler")
def BilinearSampler(data, grid, *, cudnn_off=False):
    """Reference anchor ``BilinearSampler``: sample NCHW data at grid
    (N, 2, H', W') of [-1, 1] (x, y) coords."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0             # (n, H', W')
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0

    def sample(img, yy, xx):
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1 = y0 + 1
        x1 = x0 + 1
        wy = yy - y0
        wx = xx - x0

        def at(yi, xi):
            inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            yi = jnp.clip(yi, 0, h - 1)
            xi = jnp.clip(xi, 0, w - 1)
            v = img[:, yi, xi]                          # (c, H', W')
            return jnp.where(inside[None], v, 0.0)

        return (at(y0, x0) * (1 - wy) * (1 - wx) +
                at(y0, x1) * (1 - wy) * wx +
                at(y1, x0) * wy * (1 - wx) +
                at(y1, x1) * wy * wx)

    return jax.vmap(sample)(data, gy, gx)


# activation stragglers (reference mshadow_op corpus)
@op("log_sigmoid")
def log_sigmoid(data):
    return jax.nn.log_sigmoid(data)


@op("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@op("mish")
def mish(data):
    return data * jnp.tanh(jax.nn.softplus(data))


alias("SliceChannel", "split")


# ----------------------------------------------------------------------- #
# SSD MultiBox family (reference src/operator/contrib/multibox_*.cc —
# SURVEY.md §3.1 contrib: "MultiBox* [SSD]")
# ----------------------------------------------------------------------- #

@op("_contrib_MultiBoxPrior", differentiable=False)
def MultiBoxPrior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes for one feature map: data (N, C, H, W) →
    (1, H*W*(len(sizes)+len(ratios)-1), 4) corner-format boxes in [0,1]."""
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    # anchor shapes: all sizes at ratio[0], plus size[0] at other ratios
    whs = [(s * (ratios[0] ** 0.5), s / (ratios[0] ** 0.5)) for s in sizes]
    whs += [(sizes[0] * (r ** 0.5), sizes[0] / (r ** 0.5))
            for r in ratios[1:]]
    whs = jnp.asarray(whs, jnp.float32)                # (A, 2) [w, h]
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")       # (H, W)
    centers = jnp.stack([gx, gy], axis=-1).reshape(-1, 1, 2)  # (HW, 1, 2)
    half = whs.reshape(1, -1, 2) / 2.0
    mins = centers - half
    maxs = centers + half
    boxes = jnp.concatenate([mins, maxs], axis=-1).reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


alias("MultiBoxPrior", "_contrib_MultiBoxPrior")


def _iou_matrix(a, b):
    """a: (A, 4), b: (B, 4) corner boxes → (A, B) IoU."""
    ax1, ay1, ax2, ay2 = (a[:, i, None] for i in range(4))
    bx1, by1, bx2, by2 = (b[None, :, i] for i in range(4))
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0) * jnp.maximum(ay2 - ay1, 0)
    area_b = jnp.maximum(bx2 - bx1, 0) * jnp.maximum(by2 - by1, 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@op("_contrib_MultiBoxTarget", differentiable=False)
def MultiBoxTarget(anchor, label, cls_pred, *, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets: anchors (1, A, 4), labels (N, O, 5)
    [cls, x1, y1, x2, y2] (−1 pad) → (loc_target (N, A*4),
    loc_mask (N, A*4), cls_target (N, A))."""
    A = anchor.shape[1]
    anc = anchor.reshape(A, 4)
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2
    aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
    ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
    vx, vy, vw, vh = variances

    def one(lab):
        valid = lab[:, 0] >= 0                           # (O,)
        boxes = lab[:, 1:5]
        iou = _iou_matrix(anc, boxes)                    # (A, O)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_obj = jnp.argmax(iou, axis=1)               # (A,)
        best_iou = jnp.take_along_axis(iou, best_obj[:, None],
                                       axis=1)[:, 0]
        # every gt also claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)            # (O,)
        forced = jnp.zeros(A, bool).at[best_anchor].set(valid)
        pos = jnp.logical_or(best_iou >= overlap_threshold, forced)
        gt = boxes[best_obj]                             # (A, 4)
        gcx = (gt[:, 0] + gt[:, 2]) / 2
        gcy = (gt[:, 1] + gt[:, 3]) / 2
        gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-12)
        gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
        loc = jnp.stack([(gcx - acx) / aw / vx,
                         (gcy - acy) / ah / vy,
                         jnp.log(gw / aw) / vw,
                         jnp.log(gh / ah) / vh], axis=-1)  # (A, 4)
        loc = jnp.where(pos[:, None], loc, 0.0).reshape(-1)
        mask = jnp.repeat(pos.astype(jnp.float32), 4)
        cls = jnp.where(pos, lab[best_obj, 0] + 1.0, 0.0)  # 0 = background
        return loc, mask, cls

    loc_t, loc_m, cls_t = jax.vmap(one)(label)
    return loc_t, loc_m, cls_t


alias("MultiBoxTarget", "_contrib_MultiBoxTarget")


@op("_contrib_MultiBoxDetection", differentiable=False)
def MultiBoxDetection(cls_prob, loc_pred, anchor, *, clip=True,
                      threshold=0.01, nms_threshold=0.5, force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD inference decode: class probs (N, C, A), loc offsets (N, A*4),
    anchors (1, A, 4) → (N, A, 6) rows [cls_id, score, x1, y1, x2, y2]
    (cls_id −1 = suppressed/background), NMS applied per class."""
    N, C, A = cls_prob.shape
    anc = anchor.reshape(A, 4)
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2
    aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
    ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
    vx, vy, vw, vh = variances

    def one(probs, loc):
        loc = loc.reshape(A, 4)
        cx = loc[:, 0] * vx * aw + acx
        cy = loc[:, 1] * vy * ah + acy
        w = jnp.exp(loc[:, 2] * vw) * aw
        h = jnp.exp(loc[:, 3] * vh) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor (class 0 = background)
        fg = probs[1:]                                   # (C-1, A)
        best = jnp.argmax(fg, axis=0)                    # (A,)
        score = jnp.take_along_axis(fg, best[None], axis=0)[0]
        keep = score > threshold
        cls_id = jnp.where(keep, best.astype(jnp.float32), -1.0)
        score = jnp.where(keep, score, -1.0)
        return jnp.concatenate([cls_id[:, None], score[:, None], boxes],
                               axis=-1)

    rows = jax.vmap(one)(cls_prob, loc_pred)             # (N, A, 6)
    from .registry import get_op
    nms = get_op("_contrib_box_nms")
    return nms.fn(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                  topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                  force_suppress=force_suppress)


alias("MultiBoxDetection", "_contrib_MultiBoxDetection")


@op("fft", differentiable=False)
def fft(data, *, compute_size=128):
    """Reference anchor ``_contrib_fft``: real input → interleaved
    [real, imag] along the last axis (the reference's packed layout)."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (data.shape[-1] * 2,))


@op("ifft", differentiable=False)
def ifft(data, *, compute_size=128):
    """Inverse of :func:`fft` (interleaved [real, imag] input)."""
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real * n


alias("_contrib_fft", "fft")
alias("_contrib_ifft", "ifft")


@op("_contrib_Proposal", differentiable=False)
def Proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """RCNN region-proposal op (reference anchor ``Proposal``,
    src/operator/contrib/proposal.cc): anchors over the feature grid →
    decode bbox deltas → clip → min-size filter → top-k by score → NMS →
    top post-NMS.  Static shapes throughout (argsort + box_nms), so the
    whole RPN head jits.

    cls_prob (N, 2A, H, W), bbox_pred (N, 4A, H, W), im_info (N, 3)
    [height, width, scale] → rois (N*post_nms, 5) [batch_idx, x1,y1,x2,y2]
    (+ scores (N*post_nms, 1) when ``output_score``)."""
    N, twoA, H, W = cls_prob.shape
    A = twoA // 2
    fs = float(feature_stride)
    # base anchors centered on (fs-1)/2 with area (fs*scale)^2 per ratio
    base = []
    for r in ratios:
        for s in scales:
            size = fs * fs * float(s) * float(s)
            w = jnp.sqrt(size / r)
            h = w * r
            cx = (fs - 1) / 2.0
            cy = (fs - 1) / 2.0
            base.append([cx - (w - 1) / 2, cy - (h - 1) / 2,
                         cx + (w - 1) / 2, cy + (h - 1) / 2])
    base = jnp.asarray(base, jnp.float32)                # (A, 4)
    sx = jnp.arange(W, dtype=jnp.float32) * fs
    sy = jnp.arange(H, dtype=jnp.float32) * fs
    gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([gx, gy, gx, gy], axis=-1).reshape(-1, 1, 4)
    anchors = (shifts + base[None]).reshape(-1, 4)       # (H*W*A, 4)
    K = anchors.shape[0]

    def one(probs, deltas, info):
        # foreground scores: second half of the class channel
        score = probs[A:].transpose(1, 2, 0).reshape(-1)      # (H*W*A,)
        d = deltas.transpose(1, 2, 0).reshape(-1, A, 4) \
            .reshape(H * W, A, 4).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + 0.5 * (aw - 1)
        acy = anchors[:, 1] + 0.5 * (ah - 1)
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        x1 = jnp.clip(cx - 0.5 * (w - 1), 0, info[1] - 1)
        y1 = jnp.clip(cy - 0.5 * (h - 1), 0, info[0] - 1)
        x2 = jnp.clip(cx + 0.5 * (w - 1), 0, info[1] - 1)
        y2 = jnp.clip(cy + 0.5 * (h - 1), 0, info[0] - 1)
        min_sz = rpn_min_size * info[2]
        ok = ((x2 - x1 + 1) >= min_sz) & ((y2 - y1 + 1) >= min_sz)
        score = jnp.where(ok, score, -1.0)
        pre = builtins.min(rpn_pre_nms_top_n, K)
        order = jnp.argsort(-score)[:pre]
        rows = jnp.stack([jnp.zeros(pre), score[order], x1[order],
                          y1[order], x2[order], y2[order]], axis=-1)
        from .registry import get_op
        nms = get_op("_contrib_box_nms")
        kept = nms.fn(rows, overlap_thresh=threshold, valid_thresh=0.0,
                      topk=-1, coord_start=2, score_index=1, id_index=0,
                      force_suppress=True)
        post = builtins.min(rpn_post_nms_top_n, pre)
        order2 = jnp.argsort(-kept[:, 1])[:post]
        sel = kept[order2]
        return sel[:, 2:6], sel[:, 1:2]

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    post = boxes.shape[1]
    batch_idx = jnp.repeat(jnp.arange(N, dtype=jnp.float32), post)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(-1, 4)], axis=-1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


alias("Proposal", "_contrib_Proposal")


@op("_contrib_DeformableConvolution")
def DeformableConvolution(data, offset, weight, bias=None, *, kernel=(),
                          stride=(), dilate=(), pad=(), num_filter=0,
                          num_group=1, num_deformable_group=1, no_bias=False,
                          layout="NCHW", workspace=1024):
    """Deformable conv v1 (reference anchor ``DeformableConvolution``,
    src/operator/contrib/deformable_convolution.cc).

    data (N, C, H, W); offset (N, 2*G*kh*kw, Ho, Wo) with (dy, dx) pairs per
    deformable group G and kernel tap.  TPU-native formulation: bilinear
    im2col gather at the offset sample points (vectorized — no scalar
    loops), then ONE big (N·Ho·Wo, C·kh·kw) × (C·kh·kw, F) MXU matmul."""
    kh, kw = kernel
    sh, sw = _pair(stride or 1, 2)
    dh, dw = _pair(dilate or 1, 2)
    ph, pw = _pair(pad or 0, 2)
    N, C, H, W = data.shape
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    G = num_deformable_group
    K = kh * kw

    # base sampling grid per output position and tap (dilated kernel)
    oy = jnp.arange(Ho) * sh - ph                       # (Ho,)
    ox = jnp.arange(Wo) * sw - pw
    ky = jnp.arange(kh) * dh                            # (kh,)
    kx = jnp.arange(kw) * dw
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # (Ho,1,kh,1)
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # (1,Wo,1,kw)
    base_y = jnp.broadcast_to(base_y, (Ho, Wo, kh, kw)).reshape(Ho, Wo, K)
    base_x = jnp.broadcast_to(base_x, (Ho, Wo, kh, kw)).reshape(Ho, Wo, K)

    off = offset.reshape(N, G, K, 2, Ho, Wo)
    dy = jnp.moveaxis(off[:, :, :, 0], (1, 2), (3, 4))  # (N, Ho, Wo, G, K)
    dx = jnp.moveaxis(off[:, :, :, 1], (1, 2), (3, 4))
    sy = base_y[None, :, :, None, :] + dy               # (N, Ho, Wo, G, K)
    sx = base_x[None, :, :, None, :] + dx

    def sample_image(img, yy, xx):
        """img (C, H, W); yy/xx (Ho, Wo, G, K) → (C, Ho, Wo, G, K)."""
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        wy = yy - y0
        wx = xx - x0

        def at(yi, xi):
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yi = jnp.clip(yi, 0, H - 1)
            xi = jnp.clip(xi, 0, W - 1)
            v = img[:, yi, xi]                          # (C, Ho, Wo, G, K)
            return jnp.where(inside[None], v, 0.0)

        return (at(y0, x0) * (1 - wy) * (1 - wx) +
                at(y0, x0 + 1) * (1 - wy) * wx +
                at(y0 + 1, x0) * wy * (1 - wx) +
                at(y0 + 1, x0 + 1) * wy * wx)

    cols = jax.vmap(sample_image)(data, sy, sx)         # (N,C,Ho,Wo,G,K)
    # deformable groups: channel block g samples with offset group g
    Cg = C // G
    cols = cols.reshape(N, G, Cg, Ho, Wo, G, K)
    cols = jnp.take_along_axis(
        cols, jnp.arange(G).reshape(1, G, 1, 1, 1, 1, 1), axis=5)[:, :, :, :, :, 0]
    cols = cols.reshape(N, C, Ho, Wo, K)
    # one MXU GEMM: (N*Ho*Wo, C*K) x (C*K, F)
    cols2 = jnp.moveaxis(cols, (2, 3), (1, 2)).reshape(N * Ho * Wo, C * K)
    wmat = weight.reshape(num_filter, C * K).T
    out = jnp.matmul(cols2, wmat).reshape(N, Ho, Wo, num_filter)
    out = jnp.moveaxis(out, 3, 1)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


alias("DeformableConvolution", "_contrib_DeformableConvolution")


@op("Correlation")
def Correlation(data1, data2, *, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (reference anchor ``Correlation``,
    src/operator/correlation.cc): for every displacement (dy, dx) within
    ``max_displacement`` (step ``stride2``), the per-pixel patch
    correlation of data1 against shifted data2.

    Vectorized as one shifted multiply + box-sum per displacement (the
    displacement count is static, so the whole op jits to a fused loop).
    Output: (N, D*D, Ho, Wo) with D = 2*floor(max_displacement/stride2)+1
    and Ho = ceil((H + 2*pad - 2*border) / stride1) where
    border = max_displacement + (kernel_size-1)//2 — the reference crops
    that border from the padded grid before striding."""
    N, C, H, W = data1.shape
    p = pad_size
    a = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    b = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    Hp, Wp = H + 2 * p, W + 2 * p
    steps = max_displacement // stride2
    disps = [d * stride2 for d in range(-steps, steps + 1)]
    outs = []
    for dy in disps:
        for dx in disps:
            shifted = jnp.roll(b, shift=(-dy, -dx), axis=(2, 3))
            valid_y = jnp.zeros(Hp, bool).at[
                max(0, -dy):Hp - max(0, dy)].set(True)
            valid_x = jnp.zeros(Wp, bool).at[
                max(0, -dx):Wp - max(0, dx)].set(True)
            mask = valid_y[:, None] & valid_x[None, :]
            prod = (a * shifted if is_multiply
                    else jnp.abs(a - shifted))
            corr = prod.mean(axis=1) * mask[None]        # (N, Hp, Wp)
            if kernel_size > 1:
                corr = lax.reduce_window(
                    corr, 0.0, lax.add, (1, kernel_size, kernel_size),
                    (1, 1, 1), "SAME") / (kernel_size * kernel_size)
            outs.append(corr)
    out = jnp.stack(outs, axis=1)                        # (N, D*D, Hp, Wp)
    # crop the reference's border (max_displacement + kernel_radius) and
    # anchor stride1 sampling after it; within the crop every displaced
    # window stays in-bounds so the zero-masking above never bites
    border = max_displacement + (kernel_size - 1) // 2
    out = out[:, :, border:Hp - border:stride1, border:Wp - border:stride1]
    return out
