"""Registered random-sampling + remaining fused-optimizer + quantized ops.

Reference families (SURVEY.md §3.1 operator corpus):
- ``_random_*`` ops (``random_uniform``...): tensor-shaped draws with
  scalar parameters.
- ``sample_*`` ops: PER-ROW parameter arrays — ``sample_normal(mu, sigma,
  shape=(s,))`` draws ``s`` values for every element of ``mu``.
- ``preloaded_multi_*`` / ``multi_adamw`` / ``multi_lamb`` fused
  multi-tensor optimizer updates (variadic — whole parameter lists in one
  op, the reference's ``aggregate_num`` path).
- int8 ``quantized_*`` inference ops beyond conv/matmul.

RNG keys come from ``mxnet_tpu.random`` (seeded, trace-aware), matching
the reference's per-device RNG resource (anchor
``ResourceRequest::kRandom``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import alias, op

__all__: list = []


def _key():
    from .. import random as mxrandom
    return mxrandom.next_key()


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


# --------------------------------------------------------------------------- #
# _random_* (scalar-parameter draws)
# --------------------------------------------------------------------------- #

@op("_random_uniform", differentiable=False)
def _random_uniform(*, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(_key(), _shape(shape), jnp.dtype(dtype),
                              low, high)


@op("_random_normal", differentiable=False)
def _random_normal(*, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(_key(), _shape(shape),
                                           jnp.dtype(dtype))


@op("_random_gamma", differentiable=False)
def _random_gamma(*, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(_key(), alpha, _shape(shape),
                                   jnp.dtype(dtype))


@op("_random_exponential", differentiable=False)
def _random_exponential(*, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(_key(), _shape(shape),
                                  jnp.dtype(dtype)) / lam


@op("_random_poisson", differentiable=False)
def _random_poisson(*, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(_key(), lam, _shape(shape)).astype(
        jnp.dtype(dtype))


@op("_random_negative_binomial", differentiable=False)
def _random_negative_binomial(*, k=1, p=0.5, shape=(), dtype="float32"):
    g = jax.random.gamma(_key(), k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(_key(), g, _shape(shape)).astype(
        jnp.dtype(dtype))


@op("_random_generalized_negative_binomial", differentiable=False)
def _random_generalized_negative_binomial(*, mu=1.0, alpha=1.0, shape=(),
                                          dtype="float32"):
    k = 1.0 / alpha
    p = k / (k + mu)
    g = jax.random.gamma(_key(), k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(_key(), g, _shape(shape)).astype(
        jnp.dtype(dtype))


@op("_random_randint", differentiable=False)
def _random_randint(*, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(_key(), _shape(shape), low, high,
                              jnp.dtype(dtype))


# --------------------------------------------------------------------------- #
# sample_* (per-element parameter arrays; draws `shape` extra dims)
# --------------------------------------------------------------------------- #

def _sample(draw, param0, extra_shape):
    s = _shape(extra_shape)
    out_shape = tuple(param0.shape) + s
    return draw(out_shape)


@op("sample_uniform", differentiable=False)
def sample_uniform(low, high, *, shape=(), dtype="float32"):
    s = tuple(low.shape) + _shape(shape)
    u = jax.random.uniform(_key(), s, jnp.dtype(dtype))
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    return low[ex] + (high - low)[ex] * u


@op("sample_normal", differentiable=False)
def sample_normal(mu, sigma, *, shape=(), dtype="float32"):
    s = tuple(mu.shape) + _shape(shape)
    n = jax.random.normal(_key(), s, jnp.dtype(dtype))
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    return mu[ex] + sigma[ex] * n


@op("sample_gamma", differentiable=False)
def sample_gamma(alpha, beta, *, shape=(), dtype="float32"):
    s = tuple(alpha.shape) + _shape(shape)
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    a = jnp.broadcast_to(alpha[ex], s)
    g = jax.random.gamma(_key(), a, s, jnp.dtype(dtype))
    return g * jnp.broadcast_to(beta[ex], s)


@op("sample_exponential", differentiable=False)
def sample_exponential(lam, *, shape=(), dtype="float32"):
    s = tuple(lam.shape) + _shape(shape)
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    return jax.random.exponential(_key(), s, jnp.dtype(dtype)) / \
        jnp.broadcast_to(lam[ex], s)


@op("sample_poisson", differentiable=False)
def sample_poisson(lam, *, shape=(), dtype="float32"):
    s = tuple(lam.shape) + _shape(shape)
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    return jax.random.poisson(_key(), jnp.broadcast_to(lam[ex], s),
                              s).astype(jnp.dtype(dtype))


def _nb_mixture(kk, pp, s, dtype):
    """Shared gamma–Poisson NB mixture over broadcast (k, p) arrays."""
    g = jax.random.gamma(_key(), kk, s) * \
        ((1.0 - pp) / jnp.maximum(pp, 1e-12))
    return jax.random.poisson(_key(), g, s).astype(jnp.dtype(dtype))


@op("sample_negative_binomial", differentiable=False)
def sample_negative_binomial(k, p, *, shape=(), dtype="float32"):
    """Per-element NB(k, p) draws (reference ``sample_negative_binomial``):
    Poisson–gamma mixture, matching ``_random_negative_binomial``."""
    s = tuple(k.shape) + _shape(shape)
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    kk = jnp.broadcast_to(k[ex], s).astype(jnp.float32)
    pp = jnp.broadcast_to(p[ex], s).astype(jnp.float32)
    return _nb_mixture(kk, pp, s, dtype)


@op("sample_generalized_negative_binomial", differentiable=False)
def sample_generalized_negative_binomial(mu, alpha, *, shape=(),
                                         dtype="float32"):
    """Per-element GNB(mu, alpha) draws: k = 1/alpha, p = k/(k+mu) —
    matching ``_random_generalized_negative_binomial``."""
    s = tuple(mu.shape) + _shape(shape)
    ex = (Ellipsis,) + (None,) * len(_shape(shape))
    mm = jnp.broadcast_to(mu[ex], s).astype(jnp.float32)
    aa = jnp.broadcast_to(alpha[ex], s).astype(jnp.float32)
    kk = 1.0 / jnp.maximum(aa, 1e-12)
    pp = kk / (kk + mm)
    return _nb_mixture(kk, pp, s, dtype)


@op("sample_multinomial", differentiable=False)
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32"):
    """Rows of ``data`` are probability vectors; draw ``shape`` samples
    per row (reference ``sample_multinomial``)."""
    s = _shape(shape)
    n = 1
    for d in s:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-30))
    draws = jax.random.categorical(
        _key(), logits[..., None, :].repeat(max(n, 1), axis=-2), axis=-1)
    out = draws.reshape(tuple(data.shape[:-1]) + s) if s else \
        draws.reshape(tuple(data.shape[:-1]))
    out = out.astype(jnp.dtype(dtype))
    if get_prob:
        p = jnp.take_along_axis(
            data, out.reshape(tuple(data.shape[:-1]) + (-1,)).astype(
                jnp.int32), axis=-1).reshape(out.shape)
        return out, jnp.log(p)
    return out


# --------------------------------------------------------------------------- #
# fused multi-tensor optimizer ops (variadic, reference aggregate path)
# --------------------------------------------------------------------------- #

def _chunk(args, n_per):
    n = len(args) // n_per
    return [args[i * n_per:(i + 1) * n_per] for i in range(n)]


@op("multi_adamw_update", variadic=True)
def multi_adamw_update(*args, lrs, etas, beta1=0.9, beta2=0.999,
                       epsilon=1e-8, wds=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, step_count=1):
    """Fused AdamW over N params: args = [w0,g0,m0,v0, w1,g1,m1,v1, ...];
    returns the updated (w, m, v) triples flattened."""
    groups = _chunk(list(args), 4)
    n = len(groups)
    lrs = lrs if isinstance(lrs, (list, tuple)) else [lrs] * n
    etas = etas if isinstance(etas, (list, tuple)) else [etas] * n
    wds = wds if isinstance(wds, (list, tuple)) else [wds] * n
    outs = []
    for (w, g, m, v), lr, eta, wd in zip(groups, lrs, etas, wds):
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nm = beta1 * m + (1 - beta1) * g
        nv = beta2 * v + (1 - beta2) * g * g
        mhat = nm / (1 - beta1 ** step_count)
        vhat = nv / (1 - beta2 ** step_count)
        nw = w.astype(jnp.float32) - eta * (
            lr * mhat / (jnp.sqrt(vhat) + epsilon) + wd * w.astype(
                jnp.float32))
        outs += [nw.astype(w.dtype), nm.astype(m.dtype),
                 nv.astype(v.dtype)]
    return tuple(outs)


@op("multi_lamb_update", variadic=True)
def multi_lamb_update(*args, learning_rates, wds=0.0, beta1=0.9,
                      beta2=0.999, epsilon=1e-6, step_count=1,
                      rescale_grad=1.0, lower_bound=-1.0,
                      upper_bound=-1.0, clip_gradient=-1.0,
                      bias_correction=True):
    """Fused LAMB over N params (reference ``multi_lamb_update``)."""
    groups = _chunk(list(args), 4)
    n = len(groups)
    lrs = learning_rates if isinstance(learning_rates, (list, tuple)) \
        else [learning_rates] * n
    wds = wds if isinstance(wds, (list, tuple)) else [wds] * n
    outs = []
    for (w, g, m, v), lr, wd in zip(groups, lrs, wds):
        w32 = w.astype(jnp.float32)
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nm = beta1 * m + (1 - beta1) * g
        nv = beta2 * v + (1 - beta2) * g * g
        if bias_correction:
            mhat = nm / (1 - beta1 ** step_count)
            vhat = nv / (1 - beta2 ** step_count)
        else:
            mhat, vhat = nm, nv
        upd = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w32
        wnorm = jnp.linalg.norm(w32)
        if lower_bound > 0:
            wnorm = jnp.maximum(wnorm, lower_bound)
        if upper_bound > 0:
            wnorm = jnp.minimum(wnorm, upper_bound)
        unorm = jnp.linalg.norm(upd)
        trust = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
        outs += [(w32 - lr * trust * upd).astype(w.dtype),
                 nm.astype(m.dtype), nv.astype(v.dtype)]
    return tuple(outs)


@op("preloaded_multi_sgd_update", variadic=True)
def preloaded_multi_sgd_update(*args, rescale_grad=1.0,
                               clip_gradient=-1.0):
    """Reference ``preloaded_multi_sgd_update``: [w0,g0, w1,g1, ..., lrs,
    wds] — the learning rates/wds ride as ARRAYS (preloaded on device)."""
    lrs, wds = args[-2], args[-1]
    groups = _chunk(list(args[:-2]), 2)
    outs = []
    for i, (w, g) in enumerate(groups):
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nw = w.astype(jnp.float32) - lrs[i] * (g + wds[i] * w.astype(
            jnp.float32))
        outs.append(nw.astype(w.dtype))
    return tuple(outs)


@op("preloaded_multi_sgd_mom_update", variadic=True)
def preloaded_multi_sgd_mom_update(*args, momentum=0.9, rescale_grad=1.0,
                                   clip_gradient=-1.0):
    """[w0,g0,mom0, ..., lrs, wds] with device-resident lrs/wds."""
    lrs, wds = args[-2], args[-1]
    groups = _chunk(list(args[:-2]), 3)
    outs = []
    for i, (w, g, mom) in enumerate(groups):
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        nmom = momentum * mom - lrs[i] * (g + wds[i] * w.astype(
            jnp.float32))
        outs += [(w.astype(jnp.float32) + nmom).astype(w.dtype),
                 nmom.astype(mom.dtype)]
    return tuple(outs)


# --------------------------------------------------------------------------- #
# additional int8 inference ops
# --------------------------------------------------------------------------- #

# int8 affine convention shared by the quantized_* ops below:
#   real = (q + 128) * scale + min,  scale = (max - min) / 255
# (q = -128 maps to min, q = 127 to max)

@op("quantized_pooling_int8", differentiable=False)
def quantized_pooling_int8(data, min_data, max_data, *, kernel=(),
                           pool_type="max", stride=(), pad=(),
                           global_pool=False):
    """int8 pooling: max-pool runs directly on int8 (order-preserving);
    avg-pool dequantizes per-tile (reference ``_contrib_quantized_pooling``)."""
    from .nn import Pooling
    if pool_type == "max":
        out = Pooling.__wrapped__(data, kernel=kernel, pool_type="max",
                                  stride=stride, pad=pad,
                                  global_pool=global_pool)
        return out, min_data, max_data
    scale = jnp.maximum(max_data - min_data, 1e-12) / 255.0
    x = (data.astype(jnp.float32) + 128.0) * scale + min_data
    out = Pooling.__wrapped__(x, kernel=kernel, pool_type=pool_type,
                              stride=stride, pad=pad,
                              global_pool=global_pool)
    q = jnp.clip(jnp.round((out - min_data) / scale) - 128.0,
                 -128, 127).astype(jnp.int8)
    return q, min_data, max_data


@op("quantized_act_int8", differentiable=False)
def quantized_act_int8(data, min_data, max_data, *, act_type="relu"):
    """int8 ReLU: clamp at the zero point; the calibrated range is
    returned UNCHANGED so consumers dequantize the clamped values
    correctly (reference ``_contrib_quantized_act``)."""
    if act_type != "relu":
        raise ValueError(f"quantized_act_int8: unsupported {act_type}")
    scale = jnp.maximum(max_data - min_data, 1e-12) / 255.0
    # ceil: the clamp floor is the smallest NON-NEGATIVE representable
    # value (relu output must dequantize to >= 0)
    zero = jnp.ceil(-min_data / scale) - 128.0
    out = jnp.maximum(data.astype(jnp.int32), zero.astype(jnp.int32))
    return out.astype(jnp.int8), min_data, max_data


# --------------------------------------------------------------------------- #
# small contrib stragglers
# --------------------------------------------------------------------------- #

@op("_contrib_index_copy")
def index_copy(old, index, new):
    """out = old with out[index[i]] = new[i] (reference contrib op)."""
    return old.at[index.astype(jnp.int32)].set(new.astype(old.dtype))


@op("_contrib_index_add")
def index_add(old, index, new):
    return old.at[index.astype(jnp.int32)].add(new.astype(old.dtype))


@op("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    """data / sqrt(last_dim) — the attention-scale helper op."""
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


@op("_contrib_gradientmultiplier")
def gradientmultiplier(data, *, scalar=1.0):
    """Identity forward, grad scaled by ``scalar`` (gradient-reversal
    when negative; reference contrib op)."""
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return ((g * scalar).astype(g.dtype),)

    f.defvjp(fwd, bwd)
    return f(data)


@op("quadratic")
def quadratic(data, *, a=0.0, b=0.0, c=0.0):
    """a·x² + b·x + c (the reference's tutorial example op — part of its
    public op list)."""
    return a * data * data + b * data + c


@op("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, *, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward (the KL sparsity penalty attaches in backward in
    the reference; under tape autograd the penalty is a training-script
    concern — API-parity identity, documented)."""
    return data


alias("RNN", "fused_rnn")
alias("broadcast_axes", "broadcast_axis")
alias("random_uniform", "_random_uniform")
alias("random_normal", "_random_normal")
alias("random_gamma", "_random_gamma")
alias("random_exponential", "_random_exponential")
alias("random_poisson", "_random_poisson")
alias("random_negative_binomial", "_random_negative_binomial")
alias("random_generalized_negative_binomial",
      "_random_generalized_negative_binomial")
alias("random_randint", "_random_randint")
alias("multinomial", "sample_multinomial")
alias("interp", "interp_op")
