"""Fused autoregressive decode step: ALL transformer layers in ONE
Pallas kernel per token.

Reference anchor: the decode/predict role of SURVEY.md §3.2 (the
reference serves decode through the same per-op executor as training —
hundreds of small kernel launches per token).  Measured here (BASELINE.md
decode section): the XLA scan-step decode is SEQUENCER-bound — ~230
device ops x ~2.5 us/op = 0.58 ms of the 0.65 ms batch-1 token latency,
vs a ~0.31 ms HBM weight-streaming roofline.  VERDICT r4 item 2 asks for
the op-count collapse.

Design: a decode step at batch 1 is a chain of MATVECS — every matmul
touches each weight byte exactly once, so the step is one long weight
stream through VMEM.  The kernel packs every layer's projection weights
into ONE (n_chunks, U, CW) array and walks it with a sequential grid,
double-buffered; norm / attention / activation math happens in VMEM
between chunk matmuls.  Two families share the skeleton:

  GPT (LayerNorm, fused qkv, gelu FFN — models/transformer.py cell):
    qkv phase   xn = LN1(x);  qkv[:, c] = xn @ Wchunk + b
    attn+proj   k,v -> caches (VMEM copy + async HBM write-back at pos);
                softmax(q.K^T/sqrt(D)) V  (f32 scores, exact same math
                as models/decoding.py one_token);  x2 = x + o @ Wproj
    fc1 phase   h[:, c] = act(LN2(x2) @ Wchunk + b)
    fc2 phase   y += h[:, c] . Wchunk   (f32 accumulator)
                last chunk: x = x2 + (y + b2)

  Llama (RMSNorm, split q/k/v (GQA), RoPE, SwiGLU — models/llama.py):
    qkv phase   xn = RMS1(x); [q|k|v][:, c] = xn @ Wchunk
    attn+o      RoPE(q, k) at pos (interleaved-pair rotation via lane
                rolls, ops/attention.py rope math); grouped-query
                attention against the KV-head cache; x2 = x + o @ Wo
    gate phase  g[:, c] = RMS2(x2) @ Wchunk
    up phase    h[:, c] = silu(g[:, c]) * (RMS2(x2) @ Wchunk)
    down phase  y += h[:, c] . Wchunk;  last: x = x2 + y

K/V caches stay in HBM (pl.ANY, input-output aliased); each layer's
cache is DMA'd into a double-buffered VMEM slot one layer ahead, and the
new column is written back asynchronously — token t+1's loads see it
because pallas grid steps serialize.

``quant`` streams int8 codes with per-output-channel scales instead of
bf16 (half the HBM bytes — the q8_matvec discipline: codes convert to
bf16 in VMEM, f32 MXU accumulation, rescale in the epilogue).

The result is ONE kernel launch + ~8 XLA ops (embed, final norm, LM
head, sample) per token instead of ~230 ops.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import (_compiler_params, _interpret,
                        _pallas_backend_ok as _on_tpu)

__all__ = ["fused_decode_supported", "pack_gpt_weights",
           "pack_llama_weights", "decode_step",
           "stack_decode_weights", "stacked_decode_supported"]

_VMEM_BUDGET = 12 * 1024 * 1024


def _pick_cw(u: int, f: int, kvd: int | None = None) -> int:
    """Chunk width: must tile U (CW | U covers the 3U qkv span too), F,
    and — for GQA — the KV-projection width; bounded so the
    double-buffered (U, CW) stream block stays within 8 MB of VMEM
    (the ``2 * u * cw * 2 <= 8 MiB`` check below)."""
    for cw in (1536, 1280, 1024, 896, 768, 640, 512, 384, 256, 128, 64,
               32):
        if u % cw or f % cw:
            continue
        if kvd is not None and kvd % cw:
            continue
        if 2 * u * cw * 2 <= 8 * 1024 * 1024:
            return cw
    return 0


def _family_of(cfg):
    return "llama" if getattr(cfg, "num_kv_heads", None) is not None \
        and hasattr(cfg, "rope_base") else "gpt"


def fused_decode_supported(cfg, batch, total, dtype) -> bool:
    """Fused cached decode gate: small batch, bf16, chunk-tileable
    dims, and VMEM room for the double-buffered cache slots."""
    if not _on_tpu():
        return False
    u, f = cfg.units, cfg.hidden_size
    h = cfg.num_heads
    kv = getattr(cfg, "num_kv_heads", None) or h
    if batch > 4 or str(jnp.dtype(dtype)) != "bfloat16":
        return False
    if u % h or h % kv:
        return False
    d = u // h
    kvd = kv * d
    cw = _pick_cw(u, f, kvd if kv != h else None)
    if cw == 0:
        return False
    # two cache slots for K and V each, KV heads only (the GQA saving)
    cache_vmem = 4 * batch * kv * total * d * 2
    stream_vmem = 2 * u * cw * 2
    if cache_vmem + stream_vmem + 4 * u * max(f, 3 * u) > _VMEM_BUDGET:
        return False
    return True


def stack_decode_weights(blocks):
    """Stack every block's ``decode_layer_arrays`` export into one
    (NL, ...) array per slot — the operand set of the stacked-layer
    ``lax.scan`` decode path (``models/decoding.py``).

    This is the XLA-portable sibling of ``pack_gpt_weights`` /
    ``pack_llama_weights`` (same per-family weight enumeration, no chunk
    layout): each slot rides the scan's xs axis, so the compiled step
    contains ONE layer-body's worth of HLO instead of NL unrolled
    copies.  Callers cache the result pinned on the source arrays (the
    same invalidation discipline as the Pallas packers: a train step
    rebinds parameter arrays and triggers restacking)."""
    per = [blk.decode_layer_arrays() for blk in blocks]
    keys = list(per[0])
    if any(list(p) != keys for p in per[1:]):
        from ..base import MXNetError
        raise MXNetError("stack_decode_weights: blocks export different "
                         "decode slot sets — cannot stack")
    return {k: jnp.stack([p[k] for p in per]) for k in keys}


def stacked_decode_supported(model) -> bool:
    """Gate for the stacked-layer scan decode path (XLA, any backend).

    Requires: a block family that exports ``decode_layer_arrays`` (GPT
    ``_TransformerCell`` or ``LlamaCell``), uniform geometry / norm
    epsilons / FFN activation across layers (the scan compiles ONE body
    for all of them), and materialized parameters.  Anything else falls
    back to the per-layer unrolled path, which derives its math from the
    model's own sublayers and so covers arbitrary variants."""
    blocks = getattr(model, "blocks", None)
    if not blocks or not hasattr(model, "stacked_decode_weights"):
        return False
    if not all(hasattr(b, "decode_layer_arrays") for b in blocks):
        return False
    try:
        if hasattr(blocks[0], "rms1"):            # Llama family
            eps = {(float(b.rms1._eps), float(b.rms2._eps))
                   for b in blocks}
        else:                                     # GPT family
            eps = {(float(b.ln1._eps), float(b.ln2._eps))
                   for b in blocks}
            acts = {getattr(b.ffn.fc1.act, "_act_type", None)
                    if b.ffn.fc1.act is not None else None
                    for b in blocks}
            if len(acts) != 1:
                return False
        if len(eps) != 1:
            return False
        per0 = blocks[0].decode_layer_arrays()
        for b in blocks[1:]:
            p = b.decode_layer_arrays()
            if list(p) != list(per0) or any(
                    p[k].shape != per0[k].shape
                    or p[k].dtype != per0[k].dtype for k in per0):
                return False
    except (AttributeError, TypeError):
        # un-materialized params or a structurally different variant
        return False
    return True


def _schedule(cfg):
    """Chunk schedule: list of (phase_name, n_chunks) in grid order."""
    u, f = cfg.units, cfg.hidden_size
    h = cfg.num_heads
    kv = getattr(cfg, "num_kv_heads", None) or h
    d = u // h
    kvd = kv * d
    if _family_of(cfg) == "llama":
        cw = _pick_cw(u, f, kvd if kv != h else None)
        spans = [("qkv", (u + 2 * kvd) // cw), ("proj", u // cw),
                 ("gate", f // cw), ("up", f // cw), ("down", f // cw)]
    else:
        cw = _pick_cw(u, f)
        spans = [("qkv", 3 * u // cw), ("proj", u // cw),
                 ("fc1", f // cw), ("fc2", f // cw)]
    return cw, spans


def _quant_rows(w):
    """Per-output-channel symmetric int8 (models/decoding.py
    ``_quantize_rows`` convention): w (out, in) -> (int8 codes (out, in),
    f32 scales (out,))."""
    w32 = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(w32), axis=1) / 127.0, 1e-8)
    return jnp.round(w32 / s[:, None]).astype(jnp.int8), s


def _bias_of(lyr, n, dtype):
    if getattr(lyr, "bias", None) is not None:
        return lyr.bias.data()._data
    return jnp.zeros((n,), dtype)


def _pack(layer_mats, norm_rows, cw, dtype, quant):
    """Shared packer: ``layer_mats`` yields per layer a list of
    (W (out, in), bias (out,), mode) with mode ``"col"`` (stream W^T
    column chunks, per-chunk scales) or ``"row"`` (stream W column
    chunks contracted over lanes — the output-dim scales apply after
    the sum and are returned in ``s2``)."""
    w_chunks, b_chunks, s_chunks, norms, bias2, s2 = [], [], [], [], [], []
    for mats, nrm in zip(layer_mats, norm_rows):
        tail_bias = None
        tail_scale = None
        for (w, b, mode) in mats:
            if quant:
                wq, s = _quant_rows(w)
            else:
                wq, s = w, None
            n = wq.shape[0] if mode == "col" else wq.shape[1]
            if mode == "col":
                for c in range(wq.shape[0] // cw):
                    w_chunks.append(wq[c * cw:(c + 1) * cw, :].T)
                    b_chunks.append(b[c * cw:(c + 1) * cw])
                    if quant:
                        s_chunks.append(s[c * cw:(c + 1) * cw])
            else:
                for c in range(wq.shape[1] // cw):
                    w_chunks.append(wq[:, c * cw:(c + 1) * cw])
                    b_chunks.append(jnp.zeros((cw,), dtype))
                    if quant:
                        s_chunks.append(jnp.ones((cw,), jnp.float32))
                tail_bias = b
                tail_scale = s
        bias2.append((tail_bias if tail_bias is not None
                      else jnp.zeros((nrm.shape[1],), dtype)
                      ).astype(jnp.float32))
        s2.append(tail_scale if tail_scale is not None and quant
                  else jnp.ones((nrm.shape[1],), jnp.float32))
        norms.append(nrm)
    wstream = jnp.stack(w_chunks)
    if not quant:
        wstream = wstream.astype(dtype)
    bstream = jnp.stack(b_chunks)
    if quant:
        bstream = bstream.astype(jnp.float32)
    sstream = jnp.stack(s_chunks) if quant \
        else jnp.zeros((1, 1), jnp.float32)
    return (wstream, bstream, jnp.stack(norms), jnp.stack(bias2),
            sstream, jnp.stack(s2))


def pack_gpt_weights(blocks, dtype, quant=False):
    """Stack every GPT block's projections into the stream layout:
    Wqkv^T / Wproj^T / Wfc1^T column chunks + Wfc2 lane-contraction
    chunks, each (U, CW).  Returns the traceable 6-tuple
    ``(wstream, bstream, norms (NL,4,U) f32, bias2, sstream, s2)``."""
    cell0 = blocks[0]
    u = cell0.ln1.gamma.shape[0]
    f = cell0.ffn.fc1.weight.shape[0]
    cw = _pick_cw(u, f)

    def mats():
        for blk in blocks:
            yield [
                (blk.attn.qkv.weight.data()._data,
                 _bias_of(blk.attn.qkv, 3 * u, dtype), "col"),
                (blk.attn.proj.weight.data()._data,
                 _bias_of(blk.attn.proj, u, dtype), "col"),
                (blk.ffn.fc1.weight.data()._data,
                 _bias_of(blk.ffn.fc1, f, dtype), "col"),
                (blk.ffn.fc2.weight.data()._data,
                 _bias_of(blk.ffn.fc2, u, dtype), "row"),
            ]

    def nrms():
        for blk in blocks:
            yield jnp.stack([
                blk.ln1.gamma.data()._data.astype(jnp.float32),
                blk.ln1.beta.data()._data.astype(jnp.float32),
                blk.ln2.gamma.data()._data.astype(jnp.float32),
                blk.ln2.beta.data()._data.astype(jnp.float32)])

    return _pack(mats(), nrms(), cw, dtype, quant)


def pack_llama_weights(blocks, cfg, dtype, quant=False):
    """Llama stream: q/k/v/o^T + gate^T/up^T column chunks and down
    lane-contraction chunks.  norms rows: [rms1 gamma, 0, rms2 gamma,
    0] (RMSNorm has no beta)."""
    u, f = cfg.units, cfg.hidden_size
    d = u // cfg.num_heads
    kvd = cfg.num_kv_heads * d
    cw = _pick_cw(u, f, kvd if cfg.num_kv_heads != cfg.num_heads
                  else None)

    def mats():
        for blk in blocks:
            yield [
                (blk.attn.q_proj.weight.data()._data,
                 _bias_of(blk.attn.q_proj, u, dtype), "col"),
                (blk.attn.k_proj.weight.data()._data,
                 _bias_of(blk.attn.k_proj, kvd, dtype), "col"),
                (blk.attn.v_proj.weight.data()._data,
                 _bias_of(blk.attn.v_proj, kvd, dtype), "col"),
                (blk.attn.o_proj.weight.data()._data,
                 _bias_of(blk.attn.o_proj, u, dtype), "col"),
                (blk.mlp.gate.weight.data()._data,
                 _bias_of(blk.mlp.gate, f, dtype), "col"),
                (blk.mlp.up.weight.data()._data,
                 _bias_of(blk.mlp.up, f, dtype), "col"),
                (blk.mlp.down.weight.data()._data,
                 _bias_of(blk.mlp.down, u, dtype), "row"),
            ]

    def nrms():
        z = jnp.zeros((u,), jnp.float32)
        for blk in blocks:
            yield jnp.stack([
                blk.rms1.gamma.data()._data.astype(jnp.float32), z,
                blk.rms2.gamma.data()._data.astype(jnp.float32), z])

    return _pack(mats(), nrms(), cw, dtype, quant)


def _rope_lanewise(x32, pos, inv_lane):
    """ops/attention.py ``rope`` math on a (Rows, D) f32 value without
    strided lane access: interleaved (even, odd) pairs rotate by
    theta_i = pos * inv_freq[i]; expressed with lane rolls —
      out[even d] = x[d]*cos - x[d+1]*sin
      out[odd  d] = x[d-1]*sin + x[d]*cos
    ``inv_lane`` (1, D) carries inv_freq[d // 2] per lane."""
    rows, dd = x32.shape
    theta = pos.astype(jnp.float32) * inv_lane          # (1, D)
    c = jnp.cos(theta)
    s = jnp.sin(theta)
    d_idx = lax.broadcasted_iota(jnp.int32, (rows, dd), 1)
    even = (d_idx % 2) == 0
    xl = pltpu.roll(x32, dd - 1, axis=1)                # x[d+1]
    xr = pltpu.roll(x32, 1, axis=1)                     # x[d-1]
    return x32 * c + jnp.where(even, -xl * s, xr * s)


def _make_kernel(NL, NC, B, U, F, H, KV, D, T, CW, spans, family, act,
                 eps, quant):
    scale = 1.0 / (D ** 0.5)
    G = H // KV
    KVD = KV * D
    QS = 3 * U if family == "gpt" else U + 2 * KVD
    lo = {}
    off = 0
    for name, n in spans:
        lo[name] = (off, off + n)
        off += n
    qkv_hi = lo["qkv"][1]
    proj_lo, proj_hi = lo["proj"]
    llama = family == "llama"

    if act == "gelu":
        act_fn = jax.nn.gelu
    elif act == "relu":
        act_fn = jax.nn.relu
    elif act is None:
        act_fn = lambda z: z
    else:
        raise ValueError(f"fused decode: unsupported activation {act}")

    def kernel(pos_ref, x_ref, w_ref, b_ref, s_ref, norm_ref, b2_ref,
               s2_ref, rope_ref, kh_ref, vh_ref,
               xo_ref, kh_out, vh_out,
               xres, qkv_s, x2_s, xn_s, h_s, g_s, yacc, o_s,
               kslots, vslots, load_sem, store_sem):
        j = pl.program_id(0)
        layer = j // NC
        jj = j % NC
        pos = pos_ref[0]
        slot = lax.rem(layer, 2)

        def _chunk():
            w = w_ref[0]
            return w.astype(xres.dtype) if quant else w

        def _mm(lhs):
            """lhs @ chunk: f32 MXU accumulate; quant adds the
            per-output-channel rescale + f32 bias (q8_matvec path
            parity); native callers add the bf16 bias themselves."""
            part = jnp.dot(lhs, _chunk(),
                           preferred_element_type=jnp.float32)
            if quant:
                return part * s_ref[0][None, :] + b_ref[0][None, :]
            return part

        def _cast_add_bias(part, dst_dtype):
            if quant:
                return part.astype(dst_dtype)
            return part.astype(dst_dtype) + b_ref[0]

        def _norm(val32, grow, brow):
            g = norm_ref[layer, grow]
            if llama:  # RMSNorm (ops/nn.py): f32 ms + gamma, no beta
                ms = jnp.mean(val32 * val32, axis=-1, keepdims=True)
                return val32 * lax.rsqrt(ms + eps) * g[None, :]
            b = norm_ref[layer, brow]
            mean = jnp.mean(val32, axis=-1, keepdims=True)
            var = jnp.mean((val32 - mean) ** 2, axis=-1, keepdims=True)
            inv = lax.rsqrt(var + eps)
            return (val32 - mean) * inv * g[None, :] + b[None, :]

        def _load(lyr, slt):
            for i, (src, dst) in enumerate(((kh_ref, kslots),
                                            (vh_ref, vslots))):
                pltpu.make_async_copy(
                    src.at[lyr], dst.at[slt], load_sem.at[i, slt]).start()

        def _load_wait(slt):
            for i, (src, dst) in enumerate(((kh_ref, kslots),
                                            (vh_ref, vslots))):
                pltpu.make_async_copy(
                    src.at[0], dst.at[slt], load_sem.at[i, slt]).wait()

        @pl.when(j == 0)
        def _():
            xres[:] = x_ref[:]
            _load(0, 0)

        # ---- qkv phase: xn = norm1(x); qkv[:, c] = xn @ W (+ b) ------ #
        @pl.when(jj < qkv_hi)
        def _():
            @pl.when(jj == 0)
            def _():
                xn_s[:] = _norm(xres[:].astype(jnp.float32), 0, 1
                                ).astype(xn_s.dtype)
            part = _mm(xn_s[:])
            col = jj * CW
            qkv_s[:, pl.ds(col, CW)] = _cast_add_bias(part, qkv_s.dtype)

        # ---- attention (first proj chunk) ---------------------------- #
        @pl.when(jj == proj_lo)
        def _():
            _load_wait(slot)
            q = qkv_s[:, 0:U]
            k = qkv_s[:, U:U + KVD] if llama else qkv_s[:, U:2 * U]
            v = qkv_s[:, U + KVD:U + 2 * KVD] if llama \
                else qkv_s[:, 2 * U:3 * U]
            tids = lax.broadcasted_iota(jnp.int32, (1, T), 1)
            mask = tids <= pos
            pos_f = pos.astype(jnp.float32)
            outs = []
            for b_i in range(B):
                qh = q[b_i].reshape(H, D)
                kh_new = k[b_i].reshape(KV, D)
                vh_new = v[b_i].reshape(KV, D)
                if llama:  # RoPE on q and k (f32, cast back: op parity)
                    inv = rope_ref[0][None, :]
                    qh = _rope_lanewise(qh.astype(jnp.float32), pos_f,
                                        inv).astype(qh.dtype)
                    kh_new = _rope_lanewise(
                        kh_new.astype(jnp.float32), pos_f, inv
                    ).astype(kh_new.dtype)
                kslots[slot, b_i, :, pl.ds(pos, 1), :] = \
                    kh_new.reshape(KV, 1, D)
                vslots[slot, b_i, :, pl.ds(pos, 1), :] = \
                    vh_new.reshape(KV, 1, D)
                per_kv = []
                for kv_i in range(KV):
                    qg = qh[kv_i * G:(kv_i + 1) * G]       # (G, D)
                    s = lax.dot_general(
                        qg, kslots[slot, b_i, kv_i],
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
                    s = jnp.where(mask, s, -1e30)          # (G, T)
                    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
                    per_kv.append(jnp.dot(
                        p, vslots[slot, b_i, kv_i],
                        preferred_element_type=jnp.float32))  # (G, D)
                outs.append(jnp.concatenate(per_kv, axis=0)
                            .reshape(1, U))
            o_s[:] = jnp.concatenate(outs, axis=0).astype(o_s.dtype)
            kw = pltpu.make_async_copy(
                kslots.at[slot, :, :, pl.ds(pos, 1), :],
                kh_out.at[layer, :, :, pl.ds(pos, 1), :],
                store_sem.at[0])
            vw = pltpu.make_async_copy(
                vslots.at[slot, :, :, pl.ds(pos, 1), :],
                vh_out.at[layer, :, :, pl.ds(pos, 1), :],
                store_sem.at[1])
            kw.start()
            vw.start()
            kw.wait()
            vw.wait()

        # ---- proj phase: x2[:, c] = x[:, c] + o @ W (+ b) ------------ #
        @pl.when((jj >= proj_lo) & (jj < proj_hi))
        def _():
            c = (jj - proj_lo) * CW
            r = _mm(o_s[:])
            x2_s[:, pl.ds(c, CW)] = xres[:, pl.ds(c, CW)] + \
                _cast_add_bias(r, x2_s.dtype)

            @pl.when(jj == proj_hi - 1)
            def _():
                xn_s[:] = _norm(x2_s[:].astype(jnp.float32), 2, 3
                                ).astype(xn_s.dtype)

        if llama:
            gate_lo, gate_hi = lo["gate"]
            up_lo, up_hi = lo["up"]
            down_lo = lo["down"][0]

            # ---- gate phase: g[:, c] = xn2 @ Wgate ------------------- #
            @pl.when((jj >= gate_lo) & (jj < gate_hi))
            def _():
                @pl.when((jj == gate_lo) & (layer + 1 < NL))
                def _():
                    _load(layer + 1, 1 - slot)
                c = (jj - gate_lo) * CW
                g_s[:, pl.ds(c, CW)] = \
                    _cast_add_bias(_mm(xn_s[:]), g_s.dtype)

            # ---- up phase: h[:, c] = silu(g[:, c]) * (xn2 @ Wup) ----- #
            @pl.when((jj >= up_lo) & (jj < up_hi))
            def _():
                c = (jj - up_lo) * CW
                u_c = _cast_add_bias(_mm(xn_s[:]), h_s.dtype)
                g_c = g_s[:, pl.ds(c, CW)]
                # models/llama.py mlp: g * sigmoid(g) * u, in bf16
                h_s[:, pl.ds(c, CW)] = g_c * jax.nn.sigmoid(g_c) * u_c

            # ---- down phase: y += h[:, c] . W ------------------------ #
            @pl.when(jj >= down_lo)
            def _():
                @pl.when(jj == down_lo)
                def _():
                    yacc[:] = jnp.zeros_like(yacc)
                c = (jj - down_lo) * CW
                yacc[:] += lax.dot_general(
                    h_s[:, pl.ds(c, CW)], _chunk(),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)

                @pl.when(jj == NC - 1)
                def _():
                    acc = yacc[:]
                    if quant:
                        acc = acc * s2_ref[layer][None, :]
                    y = (acc + b2_ref[layer][None, :]).astype(xres.dtype)
                    xres[:] = x2_s[:] + y

                    @pl.when(j == NL * NC - 1)
                    def _():
                        xo_ref[:] = xres[:]
        else:
            fc1_lo, fc1_hi = lo["fc1"]
            fc2_lo = lo["fc2"][0]

            # ---- fc1 phase ------------------------------------------- #
            @pl.when((jj >= fc1_lo) & (jj < fc1_hi))
            def _():
                @pl.when((jj == fc1_lo) & (layer + 1 < NL))
                def _():
                    _load(layer + 1, 1 - slot)
                c = (jj - fc1_lo) * CW
                # unfused parity: Dense casts the matmul to bf16, adds
                # the bf16 bias, then Activation runs on the bf16 value
                # (_dense_q8 likewise activates AFTER the cdtype cast)
                z = _cast_add_bias(_mm(xn_s[:]), h_s.dtype)
                h_s[:, pl.ds(c, CW)] = act_fn(z).astype(h_s.dtype)

            # ---- fc2 phase: y += h[:, c] . W  (contract lanes) ------- #
            @pl.when(jj >= fc2_lo)
            def _():
                @pl.when(jj == fc2_lo)
                def _():
                    yacc[:] = jnp.zeros_like(yacc)
                c = (jj - fc2_lo) * CW
                yacc[:] += lax.dot_general(
                    h_s[:, pl.ds(c, CW)], _chunk(),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)

                @pl.when(jj == NC - 1)
                def _():
                    acc = yacc[:]
                    if quant:  # fc2 (U,)-scales apply after the F-sum
                        acc = acc * s2_ref[layer][None, :]
                    y = (acc + b2_ref[layer][None, :]).astype(xres.dtype)
                    xres[:] = x2_s[:] + y

                    @pl.when(j == NL * NC - 1)
                    def _():
                        xo_ref[:] = xres[:]

    return kernel


@functools.partial(
    jax.jit, static_argnames=("NL", "NC", "B", "U", "F", "H", "KV", "D",
                              "T", "CW", "spans", "family", "act",
                              "eps", "quant"))
def _decode_layers(pos, x, wstream, bstream, sstream, norms, bias2, s2,
                   rope_inv, kh, vh, *,
                   NL, NC, B, U, F, H, KV, D, T, CW, spans, family,
                   act, eps, quant):
    kernel = _make_kernel(NL, NC, B, U, F, H, KV, D, T, CW, spans,
                          family, act, eps, quant)
    dtype = x.dtype
    QS = 3 * U if family == "gpt" else U + 2 * KV * D
    s_spec = (pl.BlockSpec((1, CW), lambda j, pos: (j, 0),
                           memory_space=pltpu.VMEM) if quant
              else pl.BlockSpec(memory_space=pltpu.VMEM))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(NL * NC,),
        in_specs=[
            pl.BlockSpec((B, U), lambda j, pos: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, U, CW), lambda j, pos: (j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CW), lambda j, pos: (j, 0),
                         memory_space=pltpu.VMEM),
            s_spec,                                  # scales stream
            pl.BlockSpec(memory_space=pltpu.VMEM),   # norms (NL,4,U)
            pl.BlockSpec(memory_space=pltpu.VMEM),   # bias2 (NL,U)
            pl.BlockSpec(memory_space=pltpu.VMEM),   # s2 (NL,U)
            pl.BlockSpec(memory_space=pltpu.VMEM),   # rope inv (1,D)
            pl.BlockSpec(memory_space=pl.ANY),       # k cache
            pl.BlockSpec(memory_space=pl.ANY),       # v cache
        ],
        out_specs=[
            pl.BlockSpec((B, U), lambda j, pos: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, U), dtype),               # xres
            pltpu.VMEM((B, QS), dtype),              # qkv
            pltpu.VMEM((B, U), dtype),               # x2
            pltpu.VMEM((B, U), dtype),               # xn
            pltpu.VMEM((B, F), dtype),               # h
            pltpu.VMEM((B, F if family == "llama" else 1), dtype),  # g
            pltpu.VMEM((B, U), jnp.float32),         # yacc
            pltpu.VMEM((B, U), dtype),               # o
            pltpu.VMEM((2, B, KV, T, D), dtype),     # k slots
            pltpu.VMEM((2, B, KV, T, D), dtype),     # v slots
            pltpu.SemaphoreType.DMA((2, 2)),         # load sems
            pltpu.SemaphoreType.DMA((2,)),           # store sems
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, U), dtype),
            jax.ShapeDtypeStruct(kh.shape, kh.dtype),
            jax.ShapeDtypeStruct(vh.shape, vh.dtype),
        ],
        input_output_aliases={9: 1, 10: 2},
        # NOTE: no cost_estimate — the axon remote-compile AOT path
        # fails with "Bad lhs type" when one is attached (bisected in
        # ops/conv_fused.py; same toolchain)
        compiler_params=_compiler_params(pltpu,
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(pos, x, wstream, bstream, sstream, norms, bias2, s2, rope_inv,
      kh, vh)


def decode_step(pos, x, packed, kh, vh, cfg, act, eps):
    """One fused decode step over every layer (both families).

    pos: () or (1,) int32 position; x: (B, U) hidden after embeddings;
    packed: the 6-tuple from the family packer (cw re-derived, int8
    inferred from the stream dtype); kh/vh: stacked (NL, B, KV, T, D)
    caches — returned updated (aliased in place)."""
    import numpy as onp

    wstream, bstream, norms, bias2, sstream, s2 = packed
    NL = norms.shape[0]
    B, U = x.shape
    F = cfg.hidden_size
    H = cfg.num_heads
    KV = getattr(cfg, "num_kv_heads", None) or H
    D = U // H
    T = kh.shape[3]
    family = _family_of(cfg)
    cw, spans = _schedule(cfg)
    NC = sum(n for _, n in spans)
    quant = wstream.dtype == jnp.int8
    if family == "llama":
        base = float(getattr(cfg, "rope_base", 10000.0))
        half = D // 2
        inv_freq = 1.0 / (base ** (
            onp.arange(0, half, dtype=onp.float32) * 2.0 / D))
        rope_inv = jnp.asarray(
            onp.repeat(inv_freq, 2)[None, :], jnp.float32)   # (1, D)
    else:
        rope_inv = jnp.zeros((1, D), jnp.float32)
    pos = jnp.asarray(pos, jnp.int32).reshape(1)
    return _decode_layers(
        pos, x, wstream, bstream, sstream, norms, bias2, s2, rope_inv,
        kh, vh,
        NL=NL, NC=NC, B=B, U=U, F=F, H=H, KV=KV, D=D, T=T, CW=cw,
        spans=tuple(spans), family=family, act=act, eps=float(eps),
        quant=quant)


# back-compat alias (r5 early integration name)
gpt_decode_step = decode_step
