"""Fused backward for 1x1 convolutions: dgrad + wgrad in ONE HBM pass.

Reference anchor: the cuDNN autotuned conv backward paths behind
``Convolution`` (SURVEY.md §3.1 "cuDNN autotuned conv paths",
``MXNET_CUDNN_AUTOTUNE_DEFAULT``) — there the framework picks a cuDNN
algorithm per shape; here the TPU analog picks between XLA's conv
backward and this Pallas kernel per shape class.

Why this kernel exists (VERDICT r4 item 1, BASELINE.md ResNet section):
ResNet-50's backward convs hold ~49 ms/step with the 1x1 bottleneck
convs HBM-bound (arithmetic intensity ~50 flops/byte vs the v5e ridge of
~240).  XLA lowers conv backward as TWO independent ops —

    dgrad:  dx = dy @ W        (reads dy, W;  writes dx)
    wgrad:  dW = dy^T @ x      (reads dy, x;  writes dW)

— so the large ``dy`` tensor (4x the size of ``x`` for the expand convs)
streams from HBM TWICE.  For HBM-bound shapes that's ~2x the minimum
traffic.  This kernel tiles ``dy`` through VMEM ONCE, computing the
``dx`` tile and accumulating the full ``dW`` in f32 VMEM as it goes:

    traffic:  read dy + read x + write dx   (vs  2*dy + x + dx)

A 1x1 stride-1 conv in NHWC is exactly a (P, Ci) x (Ci, Co) matmul over
the flattened batch*spatial axis P, so the whole backward is expressible
as two MXU contractions per tile with zero layout shuffling — C rides
the TPU lane dimension natively.  (NCHW would put spatial on lanes,
misaligned for every stage except 56x56 — measured in
benchmark/conv_shape_probe.py; the model zoo's ``layout="NHWC"`` mode is
the intended pairing.)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _interpret, _pallas_backend_ok as _on_tpu

__all__ = ["conv1x1_nhwc", "fused_bwd_supported"]

_VMEM_BUDGET = 12 * 1024 * 1024


def _pick_tile(p: int, ci: int, co: int, itemsize: int = 2) -> int:
    """Largest P-tile that divides ``p`` and fits the VMEM budget:
    dy tile (Tp, Co) + x/dx tiles (Tp, Ci) double-buffered, plus the
    resident W (Co, Ci) and f32 dW accumulator.  ``itemsize`` is the
    operand dtype's byte width — f32 shapes cost twice the bf16 budget,
    so the same geometry may need a smaller tile (or none at all)."""
    fixed = co * ci * (itemsize + 4)
    for tp in (1024, 896, 784, 768, 640, 512, 448, 392, 256, 196, 128,
               112, 64, 56, 32, 16):
        if p % tp:
            continue
        tiled = 2 * (tp * co * itemsize) + 4 * (tp * ci * itemsize)
        if fixed + tiled <= _VMEM_BUDGET:
            return tp
    return 0


def fused_bwd_supported(shape_in, w_shape, stride, dilate, groups,
                        itemsize: int = 2) -> bool:
    """True when the fused Pallas backward serves this conv: NHWC 2-D,
    1x1 kernel, unit stride/dilation, ungrouped, and a tile exists."""
    import os
    # DEFAULT OFF — the r5 measured-negative (BASELINE.md "conv-bwd
    # kill"): XLA's 1x1 backward pair already runs at its two-read HBM
    # roofline per shape (e.g. s1_1x1e 1.21 ms vs 1.26 roof), this
    # kernel's measured stream efficiency (63-75% of ITS roofline)
    # cancels the single-dy-read advantage (1.20 ms — a tie), and
    # in-step it FORCES the BN-backward elementwise producer to
    # materialize instead of fusing into the conv ops (ResNet-50 NHWC:
    # 153.8 ms/step fused vs 103.3 unfused).  Kept as an opt-in
    # artifact + numerics-tested reference kernel.
    if os.environ.get("MXNET_FUSED_CONV_BWD", "0") != "1":
        return False
    if not _on_tpu():
        return False
    try:
        # GSPMD cannot auto-partition a pallas_call: on a multi-chip
        # mesh the conv stays on XLA's backward (a shard_map-wrapped
        # variant is the escalation path if multi-chip vision training
        # becomes the bottleneck)
        if jax.device_count() > 1 and not _interpret():
            return False
    except Exception:
        return False
    if len(shape_in) != 4 or groups != 1:
        return False
    co, ci, kh, kw = w_shape
    if (kh, kw) != (1, 1) or tuple(stride) != (1, 1) or \
            tuple(dilate) != (1, 1):
        return False
    n, h, w_, c = shape_in
    if c != ci:
        return False
    p = n * h * w_
    return _pick_tile(p, ci, co, itemsize) > 0


def _bwd_pair_kernel(dy_ref, x_ref, w_ref, dx_ref, dw_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dy = dy_ref[:]
    # precision=DEFAULT explicitly: mxnet_tpu.base sets the ambient
    # jax_default_matmul_precision to "highest" (an f32 concern — bf16
    # MXU dots are bit-identical either way), and under "highest"
    # Mosaic rejects the transposed-lhs dot below with "Bad lhs type"
    # (bisected r5 against the identical kernel compiled without the
    # mxnet_tpu import).
    prec = lax.Precision.DEFAULT
    # dx tile: (Tp, Co) @ (Co, Ci) on the MXU, f32 accumulation
    dx_ref[:] = jnp.dot(dy, w_ref[:], precision=prec,
                        preferred_element_type=jnp.float32
                        ).astype(dx_ref.dtype)
    # dW: contract the two tiles over P.  Mosaic also rejects a
    # sublane-sublane contraction (dot_general ((0,),(0,))), so
    # transpose the dy tile IN VMEM (no HBM traffic — the whole point
    # of this kernel) and issue a standard (Co, Tp) x (Tp, Ci) matmul.
    dw_ref[:] += jnp.dot(dy.T, x_ref[:], precision=prec,
                         preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("tp",))
def _conv1x1_bwd_pair(dy2, x2, w2, tp):
    """dy2 (P, Co), x2 (P, Ci), w2 (Co, Ci) -> (dx (P, Ci) like x,
    dW (Co, Ci) f32).  One sequential grid over P tiles."""
    p, co = dy2.shape
    ci = x2.shape[1]
    grid = p // tp
    return pl.pallas_call(
        _bwd_pair_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tp, co), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tp, ci), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((co, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tp, ci), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((co, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p, ci), x2.dtype),
            jax.ShapeDtypeStruct((co, ci), jnp.float32),
        ],
        # NOTE: no cost_estimate — the axon remote-compile AOT path
        # fails with "Mosaic failed to compile TPU kernel: Bad lhs
        # type" whenever a CostEstimate is attached (bisected r5; the
        # identical kernel without it compiles and validates)
        interpret=_interpret(),
    )(dy2, x2, w2)


@jax.custom_vjp
def conv1x1_nhwc(x, w):
    """1x1 stride-1 NHWC convolution with the fused Pallas backward.
    ``x`` (N, H, W, Ci), ``w`` (Co, Ci, 1, 1) OIHW (layout-invariant
    parameters, see ops/nn.py Convolution).  Forward is the same XLA
    conv the generic path emits; only the VJP differs."""
    return _conv1x1_fwd_math(x, w)


def _conv1x1_fwd_math(x, w):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "OIHW", "NHWC"))
    return lax.conv_general_dilated(
        x, w, (1, 1), [(0, 0), (0, 0)], dimension_numbers=dn)


def _conv1x1_fwd(x, w):
    return _conv1x1_fwd_math(x, w), (x, w)


def _conv1x1_bwd(res, dy):
    x, w = res
    n, h, w_sp, ci = x.shape
    co = w.shape[0]
    p = n * h * w_sp
    tp = _pick_tile(p, ci, co, jnp.dtype(x.dtype).itemsize)
    if tp == 0:  # shape drifted past the gate: XLA fallback
        _, pullback = jax.vjp(_conv1x1_fwd_math, x, w)
        return pullback(dy)
    dx2, dw2 = _conv1x1_bwd_pair(
        dy.reshape(p, co), x.reshape(p, ci), w.reshape(co, ci), tp)
    return dx2.reshape(x.shape), dw2.astype(w.dtype).reshape(w.shape)


conv1x1_nhwc.defvjp(_conv1x1_fwd, _conv1x1_bwd)
