"""Legacy-surface and utility operators (round-3 corpus expansion).

Families covered (SURVEY.md §3.1 "Operator corpus"):
- ``im2col``/``col2im`` — the patch-extraction utilities behind the
  reference's CPU conv path (``src/operator/nn/im2col.h``); on TPU they
  are layout transforms (gather/scatter) XLA fuses, useful for custom
  conv formulations and for API parity.
- Module-era output heads: ``LinearRegressionOutput``,
  ``LogisticRegressionOutput``, ``MAERegressionOutput``, ``SVMOutput`` —
  forward is identity/sigmoid on data; their defining property is the
  *backward* (gradient = d(loss)/d(data) w.r.t. the attached label), so
  each is a ``jax.custom_vjp`` reproducing the reference gradients.
- legacy indexing: ``choose_element_0index``, ``fill_element_0index``.
- activation ops the reference registers as standalone names: ``gelu``,
  ``selu``, ``elu``, ``prelu``, ``erfc``, ``logit``.
- optimizer ops: ``group_adagrad_update`` (contrib GroupAdaGrad),
  ``lans_update`` (LANS = LAMB with normalized gradients).
- ``softmax_cross_entropy`` — fused softmax+CE (reference op of the same
  name), ``rnn_param_concat`` (flat RNN parameter packing).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import alias, op

__all__ = [
    "im2col", "col2im", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput", "SVMOutput",
    "choose_element_0index", "fill_element_0index", "gelu", "selu", "elu",
    "prelu", "erfc", "logit", "softmax_cross_entropy",
    "group_adagrad_update", "lans_update", "rnn_param_concat",
]


def _pair(v, n=2):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v + (v[-1],) * (n - len(v)) if len(v) < n else v


# --------------------------------------------------------------------------- #
# im2col / col2im (reference anchors ``im2col``/``col2im`` ops)
# --------------------------------------------------------------------------- #

@op("im2col")
def im2col(data, *, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """(N, C, H, W) -> (N, C*kh*kw, L) patch matrix, L = out_h*out_w.

    Implemented as ``lax.conv_general_dilated_patches`` — XLA lowers it to
    fused gathers (no materialized loop)."""
    kernel = _pair(kernel)
    stride = _pair(stride or 1)
    dilate = _pair(dilate or 1)
    pad = _pair(pad or 0)
    n, c = data.shape[0], data.shape[1]
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=kernel, window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dilate,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: (N, C*kh*kw, out_h, out_w)
    return patches.reshape(n, c * kernel[0] * kernel[1], -1)


@op("col2im")
def col2im(data, *, output_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Inverse of :func:`im2col`: scatter-add the (N, C*kh*kw, L) patch
    matrix back to (N, C, H, W).  Overlapping patches accumulate (the
    reference semantics)."""
    kernel = _pair(kernel)
    stride = _pair(stride or 1)
    dilate = _pair(dilate or 1)
    pad = _pair(pad or 0)
    oh, ow = _pair(output_size)
    n = data.shape[0]
    kh, kw = kernel
    c = data.shape[1] // (kh * kw)
    out_h = (oh + 2 * pad[0] - (dilate[0] * (kh - 1) + 1)) // stride[0] + 1
    out_w = (ow + 2 * pad[1] - (dilate[1] * (kw - 1) + 1)) // stride[1] + 1
    cols = data.reshape(n, c, kh, kw, out_h, out_w)
    padded = jnp.zeros((n, c, oh + 2 * pad[0], ow + 2 * pad[1]),
                       data.dtype)
    # scatter each (ki, kj) tap at its strided offsets (static py loop of
    # kh*kw scatter-adds; XLA fuses)
    for ki in range(kh):
        for kj in range(kw):
            hi = ki * dilate[0]
            wj = kj * dilate[1]
            sl = padded[:, :, hi:hi + out_h * stride[0]:stride[0],
                        wj:wj + out_w * stride[1]:stride[1]]
            padded = padded.at[
                :, :, hi:hi + out_h * stride[0]:stride[0],
                wj:wj + out_w * stride[1]:stride[1]].set(
                sl + cols[:, :, ki, kj])
    return padded[:, :, pad[0]:pad[0] + oh, pad[1]:pad[1] + ow]


# --------------------------------------------------------------------------- #
# Module-era output heads: identity-ish forward, loss-defining backward
# --------------------------------------------------------------------------- #

def _output_head(name, fwd, dgrad):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def head(data, label, grad_scale=1.0):
        return fwd(data)

    def head_fwd(data, label, grad_scale):
        return fwd(data), (data, label)

    def head_bwd(grad_scale, res, g):
        data, label = res
        # reference semantics: out_grad is ignored; the head IS the loss
        d = dgrad(data, label) * grad_scale
        return d.astype(data.dtype), jnp.zeros_like(label)

    head.defvjp(head_fwd, head_bwd)

    def wrapper(data, label, *, grad_scale=1.0):
        return head(data, label, float(grad_scale))

    wrapper.__name__ = name
    return op(name)(wrapper)


# Reference "1/m" convention: the gradient is scaled by the number of
# regression outputs PER EXAMPLE (d.size // d.shape[0]), not the batch size.
def _num_outputs(d):
    m = 1
    for s in d.shape[1:]:
        m *= s
    return max(m, 1)


LinearRegressionOutput = _output_head(
    "LinearRegressionOutput", lambda d: d,
    lambda d, l: (d - l.reshape(d.shape)) / _num_outputs(d))
MAERegressionOutput = _output_head(
    "MAERegressionOutput", lambda d: d,
    lambda d, l: jnp.sign(d - l.reshape(d.shape)) / _num_outputs(d))
LogisticRegressionOutput = _output_head(
    "LogisticRegressionOutput", jax.nn.sigmoid,
    lambda d, l: (jax.nn.sigmoid(d) - l.reshape(d.shape)) / _num_outputs(d))


@op("SVMOutput")
def SVMOutput(data, label, *, margin=1.0, regularization_coefficient=1.0,
              use_linear=False):
    """Reference anchor ``SVMOutput``: forward is identity; the hinge
    gradient flows in backward (custom vjp below)."""
    return _svm(data, label, float(margin),
                float(regularization_coefficient), bool(use_linear))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm(data, label, margin, reg, linear):
    return data


def _svm_fwd(data, label, margin, reg, linear):
    return data, (data, label)


def _svm_bwd(margin, reg, linear, res, g):
    data, label = res
    n, k = data.shape[0], data.shape[-1]
    lab = label.astype(jnp.int32).reshape(n)
    onehot = jax.nn.one_hot(lab, k, dtype=data.dtype)
    score_y = jnp.sum(data * onehot, axis=-1, keepdims=True)
    viol = (data - score_y + margin) > 0               # margin violations
    viol = jnp.logical_and(viol, onehot == 0)
    if linear:
        dwrong = jnp.where(viol, 1.0, 0.0)
    else:  # squared hinge
        dwrong = jnp.where(viol, 2.0 * (data - score_y + margin), 0.0)
    dright = -jnp.sum(dwrong, axis=-1, keepdims=True) * onehot
    d = (dwrong * (1 - onehot) + dright) * reg
    return d.astype(data.dtype), jnp.zeros_like(label)


_svm.defvjp(_svm_fwd, _svm_bwd)


# --------------------------------------------------------------------------- #
# legacy indexing
# --------------------------------------------------------------------------- #

@op("choose_element_0index")
def choose_element_0index(data, index):
    """Reference anchor ``choose_element_0index`` — row-wise pick:
    out[i] = data[i, index[i]]."""
    idx = index.astype(jnp.int32).reshape(-1)
    return jnp.take_along_axis(
        data, idx[:, None], axis=-1)[:, 0]


@op("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i] (reference anchor)."""
    idx = rhs.astype(jnp.int32).reshape(-1)
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, idx].set(mhs.reshape(-1).astype(lhs.dtype))


# --------------------------------------------------------------------------- #
# standalone activation ops
# --------------------------------------------------------------------------- #

@op("gelu")
def gelu(data, *, approximation="erf"):
    return jax.nn.gelu(data, approximate=approximation != "erf")


@op("selu")
def selu(data):
    return jax.nn.selu(data)


@op("elu")
def elu(data, *, alpha=1.0):
    return jax.nn.elu(data, alpha=alpha)


@op("prelu")
def prelu(data, gamma):
    shape = [1] * data.ndim
    if gamma.ndim and data.ndim > 1:
        shape[1] = gamma.shape[0] if gamma.shape else 1
    return jnp.where(data >= 0, data,
                     data * gamma.reshape(shape).astype(data.dtype))


@op("erfc")
def erfc(data):
    return jax.scipy.special.erfc(data)


@op("logit")
def logit(data, *, eps=None):
    x = jnp.clip(data, eps, 1 - eps) if eps else data
    return jnp.log(x) - jnp.log1p(-x)


# --------------------------------------------------------------------------- #
# fused softmax cross-entropy (reference op ``softmax_cross_entropy``)
# --------------------------------------------------------------------------- #

@op("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Scalar summed CE over the batch: -sum_i log softmax(data)_i[label_i]
    (reference op semantics: sparse labels, sum reduction)."""
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    lab = label.astype(jnp.int32).reshape(-1)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)


# --------------------------------------------------------------------------- #
# optimizer update ops
# --------------------------------------------------------------------------- #

@op("group_adagrad_update")
def group_adagrad_update(weight, grad, history, *, lr, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Contrib GroupAdaGrad (reference ``_contrib_group_adagrad_update``):
    one accumulator per ROW (group) instead of per element."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    red = tuple(range(1, g.ndim))
    new_hist = history + jnp.mean(g * g, axis=red, keepdims=True) \
        if g.ndim > 1 else history + g * g
    upd = lr * g / (jnp.sqrt(new_hist) + epsilon)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), \
        new_hist.astype(history.dtype)


@op("lans_update")
def lans_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-6, t=1, wd=0.0, rescale_grad=1.0):
    """LANS (LAMB with per-step gradient normalization; reference contrib
    ``_contrib_lans_update`` family, one fused op here)."""
    w32 = weight.astype(jnp.float32)
    g = grad.astype(jnp.float32) * rescale_grad
    g = g / jnp.maximum(jnp.linalg.norm(g), 1e-12)     # normalized grad
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * g * g
    mhat = m / (1 - beta1 ** t)
    vhat = v / (1 - beta2 ** t)
    update = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w32
    wnorm = jnp.linalg.norm(w32)
    unorm = jnp.linalg.norm(update)
    trust = jnp.where(jnp.logical_and(wnorm > 0, unorm > 0),
                      wnorm / unorm, 1.0)
    return (w32 - lr * trust * update).astype(weight.dtype), \
        m.astype(mean.dtype), v.astype(var.dtype)


@op("rnn_param_concat", variadic=True)
def rnn_param_concat(*arrays, dim=0):
    """Reference anchor ``_rnn_param_concat``: flatten + concat the RNN
    weight list into the fused parameter vector."""
    return jnp.concatenate([a.reshape(-1) if dim == 0 else a
                            for a in arrays], axis=0)


# legacy alternate names (SwapAxis already aliased in ops/defs.py)
alias("stop_gradient", "BlockGrad")
alias("crop", "slice")
alias("_contrib_group_adagrad_update", "group_adagrad_update")
