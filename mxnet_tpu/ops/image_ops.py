"""``mx.nd.image.*`` registered operators.

Reference surface: ``src/operator/image/`` (``_image_to_tensor``,
``_image_normalize``, ``_image_resize``, ``_image_crop``,
``_image_flip_left_right`` / ``_image_flip_top_bottom``,
``_image_random_*`` — SURVEY.md §3.1 operator corpus + §3.2 "io /
recordio / image" row).  Layout follows the reference: HWC or NHWC uint8/
float input; ``to_tensor`` converts to CHW float scaled to [0, 1].

These are device ops (jnp) — the host-side pipeline augmenters live in
``mxnet_tpu/image/image.py``; both exist in the reference too.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op

__all__ = ["image_to_tensor", "image_normalize", "image_resize",
           "image_crop", "image_flip_left_right", "image_flip_top_bottom",
           "image_random_flip_left_right", "image_random_flip_top_bottom",
           "image_random_brightness", "image_random_contrast",
           "image_random_saturation"]


def _is_batch(data):
    return data.ndim == 4


@op("_image_to_tensor")
def image_to_tensor(data):
    """HWC [0,255] -> CHW float32 [0,1] (reference ``ToTensor``)."""
    x = data.astype(jnp.float32) / 255.0
    if _is_batch(data):
        return jnp.transpose(x, (0, 3, 1, 2))
    return jnp.transpose(x, (2, 0, 1))


@op("_image_normalize")
def image_normalize(data, *, mean=(0.0,), std=(1.0,)):
    """CHW (or NCHW) channel-wise (x - mean) / std.  Float input only
    (the reference op errors on integer input — a silent uint8 cast-back
    would saturate to garbage)."""
    if not jnp.issubdtype(data.dtype, jnp.floating):
        raise TypeError(
            f"image_normalize: float input required, got {data.dtype} "
            "(run image.to_tensor first)")
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    c_axis = 1 if _is_batch(data) else 0
    shape = [1] * data.ndim
    shape[c_axis] = -1
    return ((data.astype(jnp.float32) - mean.reshape(shape))
            / std.reshape(shape)).astype(data.dtype)


@op("_image_resize")
def image_resize(data, *, size=None, keep_ratio=False, interp=1):
    """HWC/NHWC resize (bilinear for interp=1, nearest for 0).
    ``keep_ratio=True`` with an int ``size`` resizes the SHORTER edge to
    ``size`` (reference semantics), preserving aspect ratio."""
    method = "nearest" if interp == 0 else "bilinear"
    in_h = data.shape[-3]
    in_w = data.shape[-2]
    if isinstance(size, int):
        if keep_ratio:
            if in_h < in_w:
                h, w = size, max(1, round(in_w * size / in_h))
            else:
                h, w = max(1, round(in_h * size / in_w)), size
        else:
            h = w = size
    else:
        w, h = size  # reference passes (w, h)
    if _is_batch(data):
        shape = (data.shape[0], h, w, data.shape[3])
    else:
        shape = (h, w, data.shape[2])
    return jax.image.resize(data.astype(jnp.float32), shape,
                            method=method).astype(data.dtype)


@op("_image_crop")
def image_crop(data, *, x=0, y=0, width=1, height=1):
    if _is_batch(data):
        return data[:, y:y + height, x:x + width, :]
    return data[y:y + height, x:x + width, :]


@op("_image_flip_left_right")
def image_flip_left_right(data):
    return jnp.flip(data, axis=-2)


@op("_image_flip_top_bottom")
def image_flip_top_bottom(data):
    return jnp.flip(data, axis=-3)


def _coin(data):
    """Per-image bernoulli: shape (N, 1, 1, 1) for NHWC batches so every
    image in a batch draws independently; scalar for a single HWC image."""
    from .. import random as mxrandom
    if _is_batch(data):
        return jax.random.bernoulli(
            mxrandom.next_key(), shape=(data.shape[0], 1, 1, 1))
    return jax.random.bernoulli(mxrandom.next_key())


@op("_image_random_flip_left_right", differentiable=False)
def image_random_flip_left_right(data):
    return jnp.where(_coin(data), jnp.flip(data, axis=-2), data)


@op("_image_random_flip_top_bottom", differentiable=False)
def image_random_flip_top_bottom(data):
    return jnp.where(_coin(data), jnp.flip(data, axis=-3), data)


def _rand_factor(data, lo, hi):
    """Per-image uniform factor, broadcastable over HWC (or NHWC batch)."""
    from .. import random as mxrandom
    shape = (data.shape[0], 1, 1, 1) if _is_batch(data) else ()
    return jax.random.uniform(mxrandom.next_key(), shape, jnp.float32,
                              lo, hi)


def _photometric_dtype(data, x):
    """Float inputs keep their dtype; integer inputs return float32 (a
    cast back to uint8 would silently saturate)."""
    return x.astype(data.dtype) if jnp.issubdtype(
        data.dtype, jnp.floating) else x


@op("_image_random_brightness", differentiable=False)
def image_random_brightness(data, *, min_factor=0.5, max_factor=1.5):
    f = _rand_factor(data, min_factor, max_factor)
    return _photometric_dtype(data, data.astype(jnp.float32) * f)


@op("_image_random_contrast", differentiable=False)
def image_random_contrast(data, *, min_factor=0.5, max_factor=1.5):
    f = _rand_factor(data, min_factor, max_factor)
    x = data.astype(jnp.float32)
    # PER-IMAGE luminance-mean contrast pivot (reference coefficients)
    coef = jnp.asarray([0.299, 0.587, 0.114], jnp.float32)
    gray = jnp.mean(jnp.tensordot(x, coef, axes=([-1], [0])),
                    axis=(-2, -1), keepdims=True)[..., None]
    return _photometric_dtype(data, gray * (1 - f) + x * f)


@op("_image_random_saturation", differentiable=False)
def image_random_saturation(data, *, min_factor=0.5, max_factor=1.5):
    f = _rand_factor(data, min_factor, max_factor)
    x = data.astype(jnp.float32)
    coef = jnp.asarray([0.299, 0.587, 0.114], jnp.float32)
    gray = jnp.tensordot(x, coef, axes=([-1], [0]))[..., None]
    return _photometric_dtype(data, gray * (1 - f) + x * f)
