"""Op registry + corpus. Importing this package registers all core ops."""
from . import registry
from .registry import Op, get_op, list_ops, invoke, register
from . import defs
from . import nn
from . import attention
from . import linalg
from . import optimizer_ops
from . import extended
from . import legacy
from . import image_ops
from . import samplers
