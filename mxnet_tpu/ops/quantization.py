"""INT8 quantization ops (reference ``src/operator/quantization/``;
SURVEY.md §3.1 "Quantization": quantize_v2/dequantize/requantize + min-max
and KL-entropy calibration).

TPU stance: int8 matmuls hit the MXU with int32 accumulation via
``lax.dot_general(preferred_element_type=int32)``; quantize/dequantize are
elementwise chains XLA fuses into neighbors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

from .registry import op

__all__ = ["quantize_v2", "dequantize", "requantize",
           "quantized_matmul_int8", "quantized_conv_int8"]


@op("_contrib_quantize_v2", differentiable=False)
def quantize_v2(data, *, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """fp32 → int8 with symmetric scale from the calibrated range
    (reference ``_contrib_quantize_v2``).  Returns (q, min, max)."""
    if min_calib_range is None or max_calib_range is None:
        mx_abs = jnp.max(jnp.abs(data))
        min_r, max_r = -mx_abs, mx_abs
    else:
        min_r = jnp.asarray(min_calib_range, jnp.float32)
        max_r = jnp.asarray(max_calib_range, jnp.float32)
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    scale = 127.0 / jnp.maximum(amax, 1e-12)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, -amax.reshape(1), amax.reshape(1)


@op("_contrib_dequantize", differentiable=False)
def dequantize(data, min_range, max_range, *, out_type="float32"):
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))[0]
    return data.astype(jnp.float32) * (amax / 127.0)


@op("_contrib_requantize", differentiable=False)
def requantize(data, min_range, max_range, *, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator → int8 with a new output range (reference
    ``requantize`` after quantized conv/fc)."""
    in_amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))[0]
    real = data.astype(jnp.float32) * (in_amax / (127.0 * 127.0))
    if min_calib_range is not None and max_calib_range is not None:
        out_amax = max(abs(min_calib_range), abs(max_calib_range))
    else:
        out_amax = jnp.max(jnp.abs(real))
    scale = 127.0 / jnp.maximum(out_amax, 1e-12)
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    a = jnp.asarray(out_amax, jnp.float32).reshape(1)
    return q, -a, a


@op("quantized_matmul_int8", differentiable=False)
def quantized_matmul_int8(qa, qb, *, transpose_b=False):
    """int8 × int8 → int32 matmul (MXU path: int32 accumulation)."""
    b = qb.T if transpose_b else qb
    return lax.dot_general(
        qa, b, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def optimal_threshold_kl(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence calibration (reference ``_get_optimal_threshold``):
    pick the clip threshold whose quantized distribution diverges least
    from the observed one.  Pure numpy (host-side calibration pass)."""
    hist = onp.asarray(hist, dtype=onp.float64)
    num_bins = hist.size
    zero_bin = num_bins // 2
    thresholds = []
    divergences = []
    # scan symmetric windows around zero
    for i in range(num_quantized_bins // 2, zero_bin + 1):
        lo, hi = zero_bin - i, zero_bin + i + 1
        p = hist[lo:hi].copy()
        left_outliers = hist[:lo].sum()
        right_outliers = hist[hi:].sum()
        p[0] += left_outliers
        p[-1] += right_outliers
        # quantize p into num_quantized_bins buckets
        factor = p.size / num_quantized_bins
        q = onp.zeros_like(p)
        for j in range(num_quantized_bins):
            start = int(round(j * factor))
            stop = int(round((j + 1) * factor))
            chunk = p[start:stop]
            nz = (chunk != 0).sum()
            if nz:
                q[start:stop] = onp.where(chunk != 0, chunk.sum() / nz, 0)
        p_sum, q_sum = p.sum(), q.sum()
        if p_sum == 0 or q_sum == 0:
            continue
        pn, qn = p / p_sum, q / q_sum
        mask = (pn > 0) & (qn > 0)
        kl = (pn[mask] * onp.log(pn[mask] / qn[mask])).sum()
        thresholds.append(hist_edges[hi] if hi < hist_edges.size
                          else hist_edges[-1])
        divergences.append(kl)
    if not thresholds:
        return float(abs(hist_edges).max())
    return float(thresholds[int(onp.argmin(divergences))])


@op("quantized_conv_int8", differentiable=False)
def quantized_conv_int8(qx, qw, *, stride=(1, 1), pad=(0, 0),
                        dilate=(1, 1), num_group=1):
    """int8 NCHW convolution with int32 accumulation (reference
    ``_contrib_quantized_conv`` — the oneDNN/cuDNN int8 conv; on TPU the
    integer dot rides the MXU via ``preferred_element_type=int32``)."""
    dn = lax.conv_dimension_numbers(qx.shape, qw.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(
        qx.astype(jnp.int8), qw.astype(jnp.int8),
        window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate),
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
