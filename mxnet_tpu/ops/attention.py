"""Attention kernels: flash attention (Pallas TPU) + ring attention (SP).

Reference counterpart: the BERT-era fused attention matmuls
(``_contrib_interleaved_matmul_selfatt_qk/valatt``, SURVEY.md §3.1
"Operator corpus" contrib family) which materialize the O(L²) score matrix.
The TPU-native answer (SURVEY.md §5.7 — NEW capability, not parity) is:

- ``flash_attention``: blockwise online-softmax attention, O(L) memory.
  Forward is a Pallas kernel on TPU (MXU-tiled 128-blocks, fp32
  accumulation); everywhere else a ``lax.scan`` blockwise implementation
  that XLA fuses.  Backward recomputes blockwise from the saved
  log-sum-exp (the flash-attention-2 scheme) — no O(L²) residuals.
- ``ring_attention``: sequence-parallel attention over a mesh axis; K/V
  shards rotate around the ICI ring via ``ppermute`` while each device
  accumulates online-softmax partials for its local Q shard.  This is the
  scale-out long-context path (SURVEY.md §3.3 "SP/CP" row).

Shapes follow (batch, heads, seq, head_dim) throughout.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

__all__ = ["flash_attention", "ring_attention"]

_NEG_INF = -1e30


def _interpret() -> bool:
    # run the Pallas kernel in interpreter mode (CPU numerics testing)
    return os.environ.get("MXNET_FLASH_INTERPRET", "") == "1"


def _use_pallas() -> bool:
    env = os.environ.get("MXNET_USE_FLASH_ATTENTION", "").lower()
    if env in ("0", "false", "off"):
        return False
    if _interpret():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# blockwise reference path (runs everywhere; O(L) memory via scan)
# ---------------------------------------------------------------------------

def _blockwise_attn(q, k, v, bias, scale, causal, q_block):
    """Online-softmax attention, scanning over q blocks.  Returns
    (out, lse) with lse = logsumexp of scores per query row (fp32).
    ``bias`` is an optional additive score bias broadcastable to
    (B, H, Lq, Lk) — the padding-mask channel."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    nq = -(-Lq // q_block)
    pad_q = nq * q_block - Lq
    qf = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    qf = qf.reshape(B, H, nq, q_block, D)
    if bias is not None:
        bias = jnp.broadcast_to(
            bias.astype(jnp.float32),
            (bias.shape[0], bias.shape[1], Lq, Lk))
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, pad_q), (0, 0))) \
            if pad_q else bias
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    kpos = lax.broadcasted_iota(jnp.int32, (1, Lk), 1)

    def one_block(i, qb):
        s = jnp.einsum("bhqd,bhkd->bhqk", qb.astype(jnp.float32), k32)
        s = s * scale
        if bias is not None:
            s = s + lax.dynamic_slice_in_dim(bias, i * q_block, q_block,
                                             axis=2)
        if causal:
            qpos = i * q_block + lax.broadcasted_iota(
                jnp.int32, (q_block, 1), 0)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        m = jnp.maximum(m, -1e30)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v32) / jnp.maximum(l, 1e-30)
        lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
        return o, lse

    def scan_fn(_, xs):
        i, qb = xs
        return None, one_block(i, qb)

    _, (o, lse) = lax.scan(
        scan_fn, None, (jnp.arange(nq), jnp.moveaxis(qf, 2, 0)))
    o = jnp.moveaxis(o, 0, 2).reshape(B, H, nq * q_block, D)
    lse = jnp.moveaxis(lse, 0, 2).reshape(B, H, nq * q_block)
    if pad_q:
        o, lse = o[:, :, :Lq], lse[:, :, :Lq]
    return o.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _pallas_fwd(q, k, v, scale, causal, block_q=128, block_k=128):
    """Flash forward on TPU.  Grid (batch·heads, q_blocks, k_blocks) with
    the k axis innermost: VMEM holds one q/k/v block at a time (O(block·D)
    VMEM — long sequences stream from HBM) while running max / sum / output
    accumulators live in VMEM scratch across the k sweep.  head_dim is
    padded to the 128-lane width so every model head size hits the MXU."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, L, D0 = q.shape
    Lk = k.shape[2]
    D = max(128, -(-D0 // 128) * 128)
    if D != D0:
        padd = ((0, 0), (0, 0), (0, 0), (0, D - D0))
        q = jnp.pad(q, padd)
        k = jnp.pad(k, padd)
        v = jnp.pad(v, padd)
    nq = L // block_q
    nk = Lk // block_k

    # m/l scratch live at full 128-lane width (the value broadcast across
    # lanes) — TPU vregs are (8, 128); a lane-1 scratch would not tile.
    LANES = 128

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s):
        qi = pl.program_id(1)
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            m_s[:] = jnp.full_like(m_s, _NEG_INF)
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        run = True
        if causal:
            # skip fully-masked blocks above the diagonal
            run = (qi + 1) * block_q > kj * block_k

        @pl.when(run if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                qpos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, 1), 0)
                kpos = kj * block_k + lax.broadcasted_iota(
                    jnp.int32, (1, block_k), 1)
                s = jnp.where(qpos >= kpos, s, _NEG_INF)
            m_prev = m_s[:]
            m_new = jnp.maximum(
                m_prev, jnp.broadcast_to(
                    jnp.max(s, axis=-1, keepdims=True), (block_q, LANES)))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, :1])
            m_s[:] = m_new
            l_s[:] = l_s[:] * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=-1, keepdims=True), (block_q, LANES))
            acc_s[:] = acc_s[:] * alpha[:, :1] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(kj == nk - 1)
        def _finalize():
            l = jnp.maximum(l_s[:], 1e-30)
            o_ref[0] = (acc_s[:] / l[:, :1]).astype(o_ref.dtype)
            lse_ref[0] = m_s[:] + jnp.log(l)

    grid = (B * H, nq, nk)
    qr = q.reshape(B * H, L, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, L, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(qr, kr, vr)
    out = out.reshape(B, H, L, D)
    if D != D0:
        out = out[..., :D0]
    return out, lse[..., 0].reshape(B, H, L)


# ---------------------------------------------------------------------------
# custom VJP: blockwise recompute backward (flash-attention-2 scheme)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, bias, scale, causal):
    out, _ = _flash_fwd_impl(q, k, v, bias, scale, causal)
    return out


def _flash_fwd_impl(q, k, v, bias, scale, causal):
    B, H, L, D = q.shape
    Lk = k.shape[2]
    if bias is None and _use_pallas() and L % 128 == 0 and Lk % 128 == 0:
        return _pallas_fwd(q, k, v, scale, causal)
    return _blockwise_attn(q, k, v, bias, scale, causal,
                           q_block=min(128, max(16, L)))


def _flash_fwd(q, k, v, bias, scale, causal):
    out, lse = _flash_fwd_impl(q, k, v, bias, scale, causal)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(scale, causal, res, g):
    q, k, v, bias, out, lse = res
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    g32, o32 = g.astype(jnp.float32), out.astype(jnp.float32)
    # delta_i = sum_d o_i * do_i  (row-wise), standard flash backward
    delta = jnp.sum(o32 * g32, axis=-1)              # (B,H,Lq)

    block = min(512, Lk)
    nkb = -(-Lk // block)
    padk = nkb * block - Lk
    if padk:
        k32 = jnp.pad(k32, ((0, 0), (0, 0), (0, padk), (0, 0)))
        v32 = jnp.pad(v32, ((0, 0), (0, 0), (0, padk), (0, 0)))
    qpos = lax.broadcasted_iota(jnp.int32, (Lq, 1), 0)

    bias32 = None
    if bias is not None:
        bias32 = jnp.broadcast_to(
            bias.astype(jnp.float32),
            (bias.shape[0], bias.shape[1], Lq, Lk))
        if padk:
            bias32 = jnp.pad(bias32, ((0, 0), (0, 0), (0, 0), (0, padk)))

    def body(carry, j):
        dq_acc = carry
        ks = lax.dynamic_slice_in_dim(k32, j * block, block, axis=2)
        vs = lax.dynamic_slice_in_dim(v32, j * block, block, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, ks) * scale
        if bias32 is not None:
            s = s + lax.dynamic_slice_in_dim(bias32, j * block, block,
                                             axis=3)
        kpos = j * block + lax.broadcasted_iota(jnp.int32, (1, block), 1)
        valid = kpos < Lk
        if causal:
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])              # (B,H,Lq,block)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, ks)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
        if bias is None:
            dbias_blk = jnp.zeros((), jnp.float32)
        else:
            # d(bias) = ds / scale, summed over dims bias broadcasts on
            db = ds / scale
            for ax in range(3):
                if bias.shape[ax] == 1:
                    db = jnp.sum(db, axis=ax, keepdims=True)
            if bias.shape[3] == 1:
                db = jnp.sum(db, axis=3, keepdims=True)
            dbias_blk = db
        return dq_acc, (dk, dv, dbias_blk)

    dq0 = jnp.zeros_like(q32)
    dq, (dks, dvs, dbs) = lax.scan(body, dq0, jnp.arange(nkb))
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, nkb * block, D)[:, :, :Lk]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, nkb * block, D)[:, :, :Lk]
    if bias is None:
        dbias = None
    elif bias.shape[3] == 1:
        dbias = jnp.sum(dbs, axis=0).astype(bias.dtype)
    else:
        # stacked k-blocks → (b0, b1, b2, nkb*block) → trim pad
        dbias = jnp.moveaxis(dbs, 0, 3)
        dbias = dbias.reshape(*dbias.shape[:3], nkb * block)[..., :Lk]
        dbias = dbias.astype(bias.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias)


_flash.defvjp(_flash_fwd, _flash_bwd)


# below this many score elements per head, materializing the full (Lq, Lk)
# attention matrix is cheap and XLA's fused softmax beats the blockwise
# kernel's scan overhead (measured on v5e: 12 layers of L=128 attention run
# ~25% faster unblocked); the flash path takes over where O(L^2) memory
# actually matters
_PLAIN_ATTN_MAX_SCORES = 512 * 512


def _plain_attn(q, k, v, bias, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        Lq, Lk = q.shape[2], k.shape[2]
        qpos = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@op("flash_attention")
def flash_attention(q, k, v, bias=None, *, scale: Optional[float] = None,
                    causal: bool = False):
    """Memory-efficient attention over (B, H, L, D) tensors.  ``bias`` is an
    optional additive score bias broadcastable to (B, H, Lq, Lk) — use
    large negative values as a padding mask (treated as constant w.r.t.
    grad).

    Short sequences (score matrix ≤ ~512²) take an unblocked fused-softmax
    path; long sequences run the O(L)-memory blockwise kernel (Pallas on
    TPU)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if q.shape[2] * k.shape[2] <= _PLAIN_ATTN_MAX_SCORES:
        return _plain_attn(q, k, v, bias, float(scale), bool(causal))
    return _flash(q, k, v, bias, float(scale), bool(causal))


# ---------------------------------------------------------------------------
# ring attention: sequence parallelism over a mesh axis
# ---------------------------------------------------------------------------

def _ring_attn_local(q, k, v, scale, causal, axis, n_shards):
    """Runs inside shard_map: q/k/v are the LOCAL sequence shards
    (B, H, L/n, D).  K/V rotate around the ring; each step folds one
    remote block into the online softmax."""
    my = lax.axis_index(axis)
    Lloc = q.shape[2]
    q32 = q.astype(jnp.float32)
    qpos = (my * Lloc + lax.broadcasted_iota(
        jnp.int32, (Lloc, 1), 0))[None, None]       # (1,1,Lloc,1)

    def step(carry, i):
        kcur, vcur, m, l, acc = carry
        src = (my - i) % n_shards                   # whose shard we hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       kcur.astype(jnp.float32)) * scale
        if causal:
            kpos = (src * Lloc + lax.broadcasted_iota(
                jnp.int32, (1, Lloc), 1))[None, None]
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vcur.astype(jnp.float32))
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_next = lax.ppermute(kcur, axis, perm)
        v_next = lax.ppermute(vcur, axis, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    B, H, _, D = q.shape
    m0 = jnp.full((B, H, Lloc, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lloc, 1), jnp.float32)
    a0 = jnp.zeros((B, H, Lloc, D), jnp.float32)
    (kf, vf, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, a0), jnp.arange(n_shards))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


@op("ring_attention", differentiable=True)
def ring_attention(q, k, v, *, scale: Optional[float] = None,
                   causal: bool = False, axis: str = "sp",
                   mesh=None):
    """Sequence-parallel attention: inputs sharded over ``axis`` on the seq
    dim; communication is ``ppermute`` around the ring (ICI-neighbor
    traffic only, the canonical long-context pattern)."""
    from jax import shard_map
    from ..parallel.mesh import default_mesh, local_mesh_axes, P
    from jax.sharding import NamedSharding

    mesh = mesh or default_mesh()
    n = local_mesh_axes(mesh)[axis]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    seq_sharding = NamedSharding(mesh, P(None, None, axis, None))
    q = jax.device_put(q, seq_sharding)
    k = jax.device_put(k, seq_sharding)
    v = jax.device_put(v, seq_sharding)
    fn = shard_map(
        functools.partial(_ring_attn_local, scale=float(scale),
                          causal=bool(causal), axis=axis, n_shards=n),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
        check_vma=False)
    return fn(q, k, v)
