"""Attention kernels: flash attention (Pallas TPU) + ring attention (SP).

Reference counterpart: the BERT-era fused attention matmuls
(``_contrib_interleaved_matmul_selfatt_qk/valatt``, SURVEY.md §3.1
"Operator corpus" contrib family) which materialize the O(L²) score matrix.
The TPU-native answer (SURVEY.md §5.7 — NEW capability, not parity) is:

- ``flash_attention``: blockwise online-softmax attention, O(L) memory.
  On TPU both the forward AND backward run as Pallas kernels (MXU-tiled
  128-blocks, fp32 accumulation); everywhere else a ``lax.scan`` blockwise
  implementation that XLA fuses.  Padding masks (additive bias of layout
  ``(B|1, 1, 1, Lk)``) and attention dropout run INSIDE the kernels;
  general dense biases (e.g. ALiBi tables) take the XLA blockwise path.
  Backward recomputes blockwise from the saved log-sum-exp (the
  flash-attention-2 scheme) — no O(L²) residuals on any path.
- ``ring_attention``: sequence-parallel attention over a mesh axis; K/V
  shards rotate around the ICI ring via ``ppermute`` while each device
  accumulates online-softmax partials for its local Q shard.  This is the
  scale-out long-context path (SURVEY.md §3.3 "SP/CP" row).

Dropout determinism: the keep-mask is a pure position hash of
``(seed, batch·head, q_pos, k_pos)`` computed identically by the Pallas
kernels and the XLA paths, so a forward on one path and a backward
recompute on another still see the same mask.

Shapes follow (batch, heads, seq, head_dim) throughout.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

__all__ = ["flash_attention", "ring_attention", "rope"]

_NEG_INF = -1e30
_BLOCK = 128  # MXU-native q/k tile


def _interpret() -> bool:
    # run the Pallas kernels in interpreter mode (CPU numerics testing)
    # backend hatch read at trace time; the pod launcher exports MXNET_*
    # to every rank, so the read is host-uniform by deployment contract:
    # tracelint: disable=TL007 -- tools/launch.py propagates MXNET_* env to all ranks
    return os.environ.get("MXNET_FLASH_INTERPRET", "") == "1"


from .._jax_compat import compiler_params as _compiler_params


def _pallas_backend_ok() -> bool:
    """Shared Pallas backend gate (flash, q8_matvec): interpret mode or a
    real TPU backend."""
    if _interpret():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _use_pallas() -> bool:
    # backend hatch read at trace time; the pod launcher exports MXNET_*
    # to every rank, so the read is host-uniform by deployment contract:
    # tracelint: disable=TL007 -- tools/launch.py propagates MXNET_* env to all ranks
    env = os.environ.get("MXNET_USE_FLASH_ATTENTION", "").lower()
    if env in ("0", "false", "off"):
        return False
    return _pallas_backend_ok()


def _is_kmask(bias) -> bool:
    """Additive bias of layout (B|1, 1, 1, Lk) — a key padding mask."""
    return bias is not None and bias.ndim == 4 and \
        bias.shape[1] == 1 and bias.shape[2] == 1


def _pallas_eligible(q, k, bias, dtype_ok=True) -> bool:
    if not _use_pallas():
        return False
    if q.shape[2] % _BLOCK or k.shape[2] % _BLOCK:
        return False
    if bias is not None and not (_is_kmask(bias) and
                                 bias.shape[3] == k.shape[2]):
        return False
    return dtype_ok


# --------------------------------------------------------------------------- #
# dropout keep-mask: pure position hash, identical on every path
# --------------------------------------------------------------------------- #

def _hash_bits(seed, bh, qpos, kpos):
    """murmur3-style avalanche over (seed, batch·head, q, k) -> uint32.
    ``bh``/``qpos``/``kpos`` broadcast against each other; pure uint32
    elementwise ops so the Pallas TPU lowering computes bit-identical
    values to XLA."""
    u = jnp.uint32
    h = u(seed) ^ (jnp.asarray(bh).astype(jnp.uint32) * u(0x9E3779B1))
    h = h ^ (jnp.asarray(qpos).astype(jnp.uint32) * u(0x85EBCA77))
    h = h ^ (jnp.asarray(kpos).astype(jnp.uint32) * u(0xC2B2AE3D))
    h = h ^ (h >> 16)
    h = h * u(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * u(0x846CA68B)
    h = h ^ (h >> 16)
    return h


def _keep_threshold(rate: float):
    # drop iff bits < rate * 2^32  (P = rate, a python float hyperparam)
    # tracelint: disable=TL001 -- scalar cast folds at trace time
    return jnp.uint32(min(int(rate * 4294967296.0), 4294967295))


def _keep(seed, bh, qpos, kpos, rate):
    return _hash_bits(seed, bh, qpos, kpos) >= _keep_threshold(rate)


# --------------------------------------------------------------------------- #
# blockwise XLA path (runs everywhere; O(L) memory via scan over q blocks)
# --------------------------------------------------------------------------- #

def _blockwise_attn(q, k, v, bias, seed, scale, causal, dropout, q_block):
    """Online-softmax attention, scanning over q blocks.  Returns
    (out, lse) with lse = logsumexp of scores per query row (fp32).
    ``bias`` is an optional additive score bias broadcastable to
    (B, H, Lq, Lk) — the padding-mask channel."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    nq = -(-Lq // q_block)
    pad_q = nq * q_block - Lq
    qf = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    qf = qf.reshape(B, H, nq, q_block, D)
    if bias is not None:
        bias = jnp.broadcast_to(
            bias.astype(jnp.float32),
            (bias.shape[0], bias.shape[1], Lq, Lk))
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, pad_q), (0, 0))) \
            if pad_q else bias
    v32 = v.astype(jnp.float32)
    kpos = lax.broadcasted_iota(jnp.int32, (1, Lk), 1)
    bh = (lax.broadcasted_iota(jnp.int32, (B, H), 0) * H +
          lax.broadcasted_iota(jnp.int32, (B, H), 1))[..., None, None]

    def one_block(i, qb):
        s = jnp.einsum("bhqd,bhkd->bhqk", qb, k,
                       preferred_element_type=jnp.float32)
        s = s * scale
        if bias is not None:
            s = s + lax.dynamic_slice_in_dim(bias, i * q_block, q_block,
                                             axis=2)
        qpos = i * q_block + lax.broadcasted_iota(
            jnp.int32, (q_block, 1), 0)
        if causal:
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        m = jnp.maximum(m, -1e30)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            keep = _keep(seed, bh, qpos[None, None], kpos[None, None],
                         dropout)
            p = jnp.where(keep, p, 0.0) / (1.0 - dropout)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v32) / jnp.maximum(l, 1e-30)
        lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
        return o, lse

    def scan_fn(_, xs):
        i, qb = xs
        return None, one_block(i, qb)

    _, (o, lse) = lax.scan(
        scan_fn, None, (jnp.arange(nq), jnp.moveaxis(qf, 2, 0)))
    o = jnp.moveaxis(o, 0, 2).reshape(B, H, nq * q_block, D)
    lse = jnp.moveaxis(lse, 0, 2).reshape(B, H, nq * q_block)
    if pad_q:
        o, lse = o[:, :, :Lq], lse[:, :, :Lq]
    return o.astype(q.dtype), lse


# --------------------------------------------------------------------------- #
# Pallas TPU forward kernel
# --------------------------------------------------------------------------- #

def _kmask_arrays(bias, B):
    """(B|1, 1, 1, Lk) additive mask -> (Nb, 1, Lk) fp32 view for the
    kernels (middle singleton keeps the Pallas block 3D/tile-legal)."""
    return bias.astype(jnp.float32).reshape(
        bias.shape[0], 1, bias.shape[3])


def _pad_heads(x, D):
    if x.shape[-1] == D:
        return x
    return jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, D - x.shape[-1]),))


# residual layout: lse/delta are stored lane-replicated at width 128
# ((BH, L, 128)) — the same scheme as jax.experimental.pallas.ops.tpu.
# flash_attention — so the backward kernels can read (block_q, 1) columns
# without any in-kernel transpose.
_LANES = 128


def _rep(x):
    """(BH, L) -> (BH, L, 128) lane-replicated."""
    return jnp.broadcast_to(x[..., None], x.shape + (_LANES,))


def _block_q_for(L):
    """Larger q blocks at length cut k/v HBM re-streaming (traffic scales
    with L/block_q) while staying within VMEM."""
    for bq in (512, 256, 128):
        if L % bq == 0:
            return bq
    return _BLOCK


def _pallas_fwd(q, k, v, scale, causal, kmask=None, seed=None, dropout=0.0,
                block_q=None, block_k=_BLOCK):
    """Flash forward on TPU.  Grid (batch·heads, q_blocks, k_blocks) with
    the k axis innermost: VMEM holds one q/k/v block at a time (O(block·D)
    VMEM — long sequences stream from HBM) while running max / sum / output
    accumulators live in VMEM scratch across the k sweep.  head_dim is
    padded to the 128-lane width so every model head size hits the MXU.
    ``kmask`` is an optional (Nb, 1, Lk) additive bias (key padding mask);
    ``dropout``/``seed`` apply in-kernel attention dropout via the shared
    position hash."""
    if block_q is None:
        block_q = _block_q_for(q.shape[2])
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, L, D0 = q.shape
    Lk = k.shape[2]
    D = max(128, -(-D0 // 128) * 128)
    q, k, v = (_pad_heads(x, D) for x in (q, k, v))
    nq = L // block_q
    nk = Lk // block_k
    inv_keep = 1.0 / (1.0 - dropout) if dropout > 0.0 else 1.0

    def kernel(seed_ref, *refs):
        if kmask is not None:
            km_ref = refs[0]
            refs = refs[1:]
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
        bhi = pl.program_id(0)
        qi = pl.program_id(1)
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            m_s[:] = jnp.full_like(m_s, _NEG_INF)
            l_s[:] = jnp.zeros_like(l_s)
            acc_s[:] = jnp.zeros_like(acc_s)

        run = True
        if causal:
            # skip fully-masked blocks above the diagonal
            run = (qi + 1) * block_q > kj * block_k

        @pl.when(run if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if kmask is not None:
                s = s + km_ref[0]                       # (1, bk) broadcast
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            kpos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, _NEG_INF)
            m_prev = m_s[:]
            m_new = jnp.maximum(
                m_prev, jnp.broadcast_to(
                    jnp.max(s, axis=-1, keepdims=True), (block_q, _LANES)))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, :1])
            # fully-masked rows/blocks: exp(-1e30 - (-1e30)) == 1 poison
            p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
            m_s[:] = m_new
            l_s[:] = l_s[:] * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=-1, keepdims=True), (block_q, _LANES))
            if dropout > 0.0:
                keep = _keep(seed_ref[0, 0], bhi, qpos, kpos, dropout)
                p = jnp.where(keep, p, 0.0) * inv_keep
            acc_s[:] = acc_s[:] * alpha[:, :1] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(kj == nk - 1)
        def _finalize():
            l = jnp.maximum(l_s[:], 1e-30)
            o_ref[0] = (acc_s[:] / l[:, :1]).astype(o_ref.dtype)
            lse_ref[0] = m_s[:] + jnp.log(l)

    grid = (B * H, nq, nk)
    qr = q.reshape(B * H, L, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    in_specs = [
        pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    args = [jnp.full((1, 1), 0 if seed is None else seed, jnp.uint32)]
    if kmask is not None:
        Nb = kmask.shape[0]
        if Nb == 1:
            km_idx = lambda b, i, j: (0, 0, j)
        else:
            km_idx = lambda b, i, j: (b // H, 0, j)
        in_specs.append(pl.BlockSpec((1, 1, block_k), km_idx,
                                     memory_space=pltpu.VMEM))
        args.append(kmask)
    in_specs += [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    args += [qr, kr, vr]
    out, lse_rep = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, L, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_compiler_params(pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    out = out.reshape(B, H, L, D)
    if D != D0:
        out = out[..., :D0]
    return out, lse_rep[..., 0].reshape(B, H, L)


# --------------------------------------------------------------------------- #
# Pallas TPU backward kernels (flash-attention-2: recompute from lse)
# --------------------------------------------------------------------------- #

def _pallas_bwd_dq(q, k, v, g, lse_rep, dlt_rep, scale, causal, kmask=None,
                   seed=None, dropout=0.0, block_q=None, block_k=_BLOCK):
    """dq kernel: grid (BH, nq, nk), k innermost; dq accumulates in VMEM.
    ``lse_rep``/``dlt_rep`` are the lane-replicated (BH, L, 128) residuals."""
    if block_q is None:
        block_q = _block_q_for(q.shape[2])
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, L, D0 = q.shape
    Lk = k.shape[2]
    D = max(128, -(-D0 // 128) * 128)
    q, k, v, g = (_pad_heads(x, D) for x in (q, k, v, g))
    nq, nk = L // block_q, Lk // block_k
    inv_keep = 1.0 / (1.0 - dropout) if dropout > 0.0 else 1.0

    def kernel(seed_ref, *refs):
        if kmask is not None:
            km_ref = refs[0]
            refs = refs[1:]
        q_ref, k_ref, v_ref, g_ref, lse_ref, dlt_ref, dq_ref, dq_s = refs
        bhi = pl.program_id(0)
        qi = pl.program_id(1)
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            dq_s[:] = jnp.zeros_like(dq_s)

        run = True
        if causal:
            run = (qi + 1) * block_q > kj * block_k

        @pl.when(run if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            gb = g_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if kmask is not None:
                s = s + km_ref[0]
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            kpos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, _NEG_INF)
            p = jnp.exp(s - lse_ref[0][:, :1])          # (bq, bk)
            p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if dropout > 0.0:
                keep = _keep(seed_ref[0, 0], bhi, qpos, kpos, dropout)
                dp = jnp.where(keep, dp, 0.0) * inv_keep
            ds = p * (dp - dlt_ref[0][:, :1])
            dq_s[:] = dq_s[:] + scale * jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(kj == nk - 1)
        def _finalize():
            dq_ref[0] = dq_s[:].astype(dq_ref.dtype)

    grid = (B * H, nq, nk)
    in_specs = [pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                             memory_space=pltpu.SMEM)]
    args = [jnp.full((1, 1), 0 if seed is None else seed, jnp.uint32)]
    if kmask is not None:
        Nb = kmask.shape[0]
        km_idx = (lambda b, i, j: (0, 0, j)) if Nb == 1 else \
            (lambda b, i, j: (b // H, 0, j))
        in_specs.append(pl.BlockSpec((1, 1, block_k), km_idx,
                                     memory_space=pltpu.VMEM))
        args.append(kmask)
    in_specs += [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    args += [q.reshape(B * H, L, D), k.reshape(B * H, Lk, D),
             v.reshape(B * H, Lk, D), g.reshape(B * H, L, D),
             lse_rep, dlt_rep]
    dq = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return dq.reshape(B, H, L, D)[..., :D0]


def _pallas_bwd_dkv(q, k, v, g, lse_rep, dlt_rep, scale, causal, kmask=None,
                    seed=None, dropout=0.0, need_dbias=False,
                    block_q=_BLOCK, block_k=None):
    """dk/dv kernel: grid (BH, nk, nq), q innermost.  Computation stays in
    q-row orientation ((block_q, block_k) scores); dk/dv fall out of
    contractions over the q dim, so no in-kernel transposes are needed.
    Optionally also emits the q-and-lane-summed dbias for the k-mask
    layout as (BH, 1, Lk)."""
    if block_k is None:
        block_k = _block_q_for(k.shape[2])
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, L, D0 = q.shape
    Lk = k.shape[2]
    D = max(128, -(-D0 // 128) * 128)
    q, k, v, g = (_pad_heads(x, D) for x in (q, k, v, g))
    nq, nk = L // block_q, Lk // block_k
    inv_keep = 1.0 / (1.0 - dropout) if dropout > 0.0 else 1.0

    def kernel(seed_ref, *refs):
        if kmask is not None:
            km_ref = refs[0]
            refs = refs[1:]
        (q_ref, k_ref, v_ref, g_ref, lse_ref, dlt_ref) = refs[:6]
        refs = refs[6:]
        if need_dbias:
            dk_ref, dv_ref, db_ref, dk_s, dv_s, db_s = refs
        else:
            dk_ref, dv_ref, dk_s, dv_s = refs
        bhi = pl.program_id(0)
        kj = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_s[:] = jnp.zeros_like(dk_s)
            dv_s[:] = jnp.zeros_like(dv_s)
            if need_dbias:
                db_s[:] = jnp.zeros_like(db_s)

        run = True
        if causal:
            run = (qi + 1) * block_q > kj * block_k

        @pl.when(run if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            gb = g_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if kmask is not None:
                s = s + km_ref[0]
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            kpos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            if causal:
                s = jnp.where(qpos >= kpos, s, _NEG_INF)
            p = jnp.exp(s - lse_ref[0][:, :1])          # (bq, bk)
            p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            p_drop = p
            if dropout > 0.0:
                keep = _keep(seed_ref[0, 0], bhi, qpos, kpos, dropout)
                dp = jnp.where(keep, dp, 0.0) * inv_keep
                p_drop = jnp.where(keep, p, 0.0) * inv_keep
            ds = p * (dp - dlt_ref[0][:, :1])
            # contract over the q dim — outputs land k-major, no transpose
            dv_s[:] = dv_s[:] + jax.lax.dot_general(
                p_drop, gb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_s[:] = dk_s[:] + scale * jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if need_dbias:
                db_s[:] = db_s[:] + jnp.broadcast_to(
                    jnp.sum(ds, axis=0, keepdims=True), db_s.shape)

        @pl.when(qi == nq - 1)
        def _finalize():
            dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_s[:].astype(dv_ref.dtype)
            if need_dbias:
                db_ref[0] = db_s[:1]

    grid = (B * H, nk, nq)
    in_specs = [pl.BlockSpec((1, 1), lambda b, j, i: (0, 0),
                             memory_space=pltpu.SMEM)]
    args = [jnp.full((1, 1), 0 if seed is None else seed, jnp.uint32)]
    if kmask is not None:
        Nb = kmask.shape[0]
        km_idx = (lambda b, j, i: (0, 0, j)) if Nb == 1 else \
            (lambda b, j, i: (b // H, 0, j))
        in_specs.append(pl.BlockSpec((1, 1, block_k), km_idx,
                                     memory_space=pltpu.VMEM))
        args.append(kmask)
    in_specs += [
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    args += [q.reshape(B * H, L, D), k.reshape(B * H, Lk, D),
             v.reshape(B * H, Lk, D), g.reshape(B * H, L, D),
             lse_rep, dlt_rep]
    out_specs = [
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((B * H, Lk, D), jnp.float32),
        jax.ShapeDtypeStruct((B * H, Lk, D), jnp.float32),
    ]
    scratch = [pltpu.VMEM((block_k, D), jnp.float32),
               pltpu.VMEM((block_k, D), jnp.float32)]
    if need_dbias:
        out_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j),
                         memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((B * H, 1, Lk), jnp.float32))
        scratch.append(pltpu.VMEM((8, block_k), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_compiler_params(pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    dk = res[0].reshape(B, H, Lk, D)[..., :D0]
    dv = res[1].reshape(B, H, Lk, D)[..., :D0]
    dbias = res[2].reshape(B, H, Lk) if need_dbias else None
    return dk, dv, dbias



# --------------------------------------------------------------------------- #
# custom VJP: blockwise recompute backward (flash-attention-2 scheme)
# --------------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, bias, seed, scale, causal, dropout=0.0, impl="auto"):
    out, _ = _flash_fwd_impl(q, k, v, bias, seed, scale, causal, dropout,
                             impl)
    return out


def _flash_fwd_impl(q, k, v, bias, seed, scale, causal, dropout,
                    impl="auto"):
    L = q.shape[2]
    if impl != "xla" and _pallas_eligible(q, k, bias):
        kmask = _kmask_arrays(bias, q.shape[0]) if bias is not None \
            else None
        return _pallas_fwd(q, k, v, scale, causal, kmask=kmask, seed=seed,
                           dropout=dropout)
    return _blockwise_attn(q, k, v, bias, seed, scale, causal, dropout,
                           q_block=min(128, max(16, L)))


def _flash_fwd(q, k, v, bias, seed, scale, causal, dropout=0.0,
               impl="auto"):
    out, lse = _flash_fwd_impl(q, k, v, bias, seed, scale, causal, dropout,
                               impl)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_bwd(scale, causal, dropout, impl, res, g):
    q, k, v, bias, seed, out, lse = res
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    g32, o32 = g.astype(jnp.float32), out.astype(jnp.float32)
    # delta_i = sum_d o_i * do_i  (row-wise), standard flash backward
    delta = jnp.sum(o32 * g32, axis=-1)                 # (B,H,Lq)

    if impl != "xla" and _pallas_eligible(q, k, bias):
        kmask = _kmask_arrays(bias, B) if bias is not None else None
        lse_rep = _rep(lse.reshape(B * H, Lq))
        dlt_rep = _rep(delta.reshape(B * H, Lq))
        dq = _pallas_bwd_dq(q, k, v, g, lse_rep, dlt_rep, scale, causal,
                            kmask=kmask, seed=seed, dropout=dropout)
        dk, dv, dbias_bh = _pallas_bwd_dkv(
            q, k, v, g, lse_rep, dlt_rep, scale, causal, kmask=kmask,
            seed=seed, dropout=dropout, need_dbias=bias is not None)
        if bias is None:
            dbias = None
        else:
            db = dbias_bh.sum(axis=1)                   # (B, Lk): sum heads
            if bias.shape[0] == 1:
                db = db.sum(axis=0, keepdims=True)
            dbias = db.reshape(bias.shape).astype(bias.dtype)
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
                dbias, None)

    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    block = min(512, Lk)
    nkb = -(-Lk // block)
    padk = nkb * block - Lk
    if padk:
        k32 = jnp.pad(k32, ((0, 0), (0, 0), (0, padk), (0, 0)))
        v32 = jnp.pad(v32, ((0, 0), (0, 0), (0, padk), (0, 0)))
    qpos = lax.broadcasted_iota(jnp.int32, (Lq, 1), 0)
    bh = (lax.broadcasted_iota(jnp.int32, (B, H), 0) * H +
          lax.broadcasted_iota(jnp.int32, (B, H), 1))[..., None, None]

    bias32 = None
    if bias is not None:
        bias32 = jnp.broadcast_to(
            bias.astype(jnp.float32),
            (bias.shape[0], bias.shape[1], Lq, Lk))
        if padk:
            bias32 = jnp.pad(bias32, ((0, 0), (0, 0), (0, 0), (0, padk)))

    def body(carry, j):
        dq_acc = carry
        ks = lax.dynamic_slice_in_dim(k32, j * block, block, axis=2)
        vs = lax.dynamic_slice_in_dim(v32, j * block, block, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, ks) * scale
        if bias32 is not None:
            s = s + lax.dynamic_slice_in_dim(bias32, j * block, block,
                                             axis=3)
        kpos = j * block + lax.broadcasted_iota(jnp.int32, (1, block), 1)
        valid = kpos < Lk
        if causal:
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                 # (B,H,Lq,block)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, vs)
        p_drop = p
        if dropout > 0.0:
            keep = _keep(seed, bh, qpos[None, None], kpos[None, None],
                         dropout)
            dp = jnp.where(keep, dp, 0.0) / (1.0 - dropout)
            p_drop = jnp.where(keep, p, 0.0) / (1.0 - dropout)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p_drop, g32)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, ks)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
        if bias is None:
            dbias_blk = jnp.zeros((), jnp.float32)
        else:
            # d(bias) = ds / scale, summed over dims bias broadcasts on
            db = ds / scale
            for ax in range(3):
                if bias.shape[ax] == 1:
                    db = jnp.sum(db, axis=ax, keepdims=True)
            if bias.shape[3] == 1:
                db = jnp.sum(db, axis=3, keepdims=True)
            dbias_blk = db
        return dq_acc, (dk, dv, dbias_blk)

    dq0 = jnp.zeros_like(q32)
    dq, (dks, dvs, dbs) = lax.scan(body, dq0, jnp.arange(nkb))
    D_ = q.shape[3]
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, nkb * block, D_)[:, :, :Lk]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, nkb * block, D_)[:, :, :Lk]
    if bias is None:
        dbias = None
    elif bias.shape[3] == 1:
        dbias = jnp.sum(dbs, axis=0).astype(bias.dtype)
    else:
        # stacked k-blocks → (b0, b1, b2, nkb*block) → trim pad
        dbias = jnp.moveaxis(dbs, 0, 3)
        dbias = dbias.reshape(*dbias.shape[:3], nkb * block)[..., :Lk]
        dbias = dbias.astype(bias.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias, None)


_flash.defvjp(_flash_fwd, _flash_bwd)


# below this many score elements per head, materializing the full (Lq, Lk)
# attention matrix is cheap and XLA's fused softmax beats the blockwise
# kernel's scan overhead (measured on v5e: 12 layers of L=128 attention run
# ~25% faster unblocked); the flash path takes over where O(L^2) memory
# actually matters
_PLAIN_ATTN_MAX_SCORES = 512 * 512

# --------------------------------------------------------------------------- #
# measured dispatch (VERDICT r2 item 4: "chosen path == fastest measured
# path").  Constants are the crossover sequence lengths from
# ``benchmark/attention_bench.py`` on v5e (causal, B4 H8 D64, bf16) — see
# the sweep table in BASELINE.md.  Entries are (max_seq, impl); the first
# row whose bound covers max(Lq, Lk) wins.  "plain" materializes O(L²)
# scores (fused-softmax), "xla" is the blockwise lax.scan path, "pallas"
# the Pallas kernels (fwd + bwd).
# --------------------------------------------------------------------------- #
_PATH_TABLE = {
    # measured 2026-07-30 on v5e (see BASELINE.md sweep):
    #   fwd:   512 plain 0.80ms | 1k-4k xla (1.17/2.02/5.92ms, pallas
    #          1.58/3.43/10.63) | 8k pallas 38.8ms (xla 39.0)
    #   train: 512 plain 0.79ms | 1k xla 1.74ms (plain 2.12, pallas 2.27)
    #          | 2k+ pallas 6.41/22.1/78.2ms (xla 6.88/25.1/122.5)
    # (sequences <= 512 already took the plain path via
    # _PLAIN_ATTN_MAX_SCORES before the table is consulted)
    "fwd": ((4096, "xla"), (None, "pallas")),
    "train": ((1024, "xla"), (None, "pallas")),
}


def _choose_path(Lq, Lk, bias, training):
    """Pick the implementation per the measured table.  Dense biases
    (anything that is not a full-width key-padding mask) never run the
    Pallas kernels, so their long-seq rows degrade to the XLA blockwise
    path."""
    L = max(Lq, Lk)
    if Lq * Lk <= _PLAIN_ATTN_MAX_SCORES:
        return "plain"
    # pallas needs the kmask's key dim to be exactly Lk — a broadcast
    # (..., 1) bias cannot be padded into a valid kernel mask
    pallas_bias_ok = bias is None or (_is_kmask(bias) and
                                      bias.shape[3] == Lk)
    for bound, impl in _PATH_TABLE["train" if training else "fwd"]:
        if bound is None or L <= bound:
            if impl == "pallas" and (not pallas_bias_ok or
                                     not _use_pallas()):
                return "xla"
            return impl
    return "xla"


def _pad_to_block(q, k, v, bias):
    """Pad seq dims to the 128 multiple the Pallas kernels need and merge
    the padding into a key-mask bias, so real tokenized batches (e.g.
    seq 1000) still hit the kernel (VERDICT r2 item 4).  Returns
    (q, k, v, bias, orig_Lq)."""
    Lq, Lk = q.shape[2], k.shape[2]
    pq = (-Lq) % _BLOCK
    pk = (-Lk) % _BLOCK
    if not pq and not pk:
        return q, k, v, bias, Lq
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pk), (0, 0)))
    if pk or bias is not None:
        if bias is None:
            bias = jnp.zeros((1, 1, 1, Lk), q.dtype)
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pk)),
                       constant_values=_NEG_INF)
    return q, k, v, bias, Lq


def _plain_attn(q, k, v, bias, scale, causal, dropout=0.0, seed=None):
    B, H = q.shape[0], q.shape[1]
    # bf16 inputs stay bf16 into the MXU; accumulation is f32 via
    # preferred_element_type (an f32 upcast first would halve MXU rate)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    Lq, Lk = q.shape[2], k.shape[2]
    if causal:
        qpos = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout > 0.0:
        bh = (lax.broadcasted_iota(jnp.int32, (B, H), 0) * H +
              lax.broadcasted_iota(jnp.int32, (B, H), 1))[..., None, None]
        qpos = lax.broadcasted_iota(jnp.int32, (1, 1, Lq, 1), 2)
        kpos = lax.broadcasted_iota(jnp.int32, (1, 1, 1, Lk), 3)
        keep = _keep(seed, bh, qpos, kpos, dropout)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


@op("flash_attention")
def flash_attention(q, k, v, bias=None, *, scale: Optional[float] = None,
                    causal: bool = False, dropout: float = 0.0,
                    training: Optional[bool] = None):
    """Memory-efficient attention over (B, H, L, D) tensors.  ``bias`` is an
    optional additive score bias broadcastable to (B, H, Lq, Lk) — use
    large negative values as a padding mask.  Gradients propagate through
    ``bias`` on every path (summed over broadcast dims).

    ``dropout`` applies attention-probability dropout (reference: the
    Dropout inside ``MultiheadAttention``) when training — in training
    mode (``autograd.is_training()``) unless ``training`` overrides.

    The implementation is chosen from the MEASURED dispatch table
    ``_PATH_TABLE`` (benchmark/attention_bench.py sweep): short sequences
    take the unblocked fused-softmax path, the mid range the XLA blockwise
    kernel, long sequences the Pallas kernels (fwd AND bwd).  ``training``
    selects the train-tuned (fwd+bwd) vs inference-tuned column.  On the
    Pallas path 128-unaligned lengths are padded inside the op (the pad
    keys are masked via the key-mask bias channel); general dense biases
    (not a ``(B|1,1,1,Lk)`` key mask) always use the XLA paths."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if training is None:
        from .. import autograd
        training = autograd.is_training()
    rate = float(dropout) if training else 0.0
    if rate > 0.0:
        from .. import random as mxrandom
        seed = jax.random.bits(mxrandom.next_key(), dtype=jnp.uint32)
    else:
        seed = jnp.uint32(0)
    path = _choose_path(q.shape[2], k.shape[2], bias, bool(training))
    if path == "plain":
        return _plain_attn(q, k, v, bias, float(scale), bool(causal),
                           dropout=rate, seed=seed)
    if path == "pallas":
        q2, k2, v2, bias2, Lq = _pad_to_block(q, k, v, bias)
        out = _flash(q2, k2, v2, bias2, seed, float(scale), bool(causal),
                     rate, "pallas")
        return out[:, :, :Lq] if out.shape[2] != Lq else out
    return _flash(q, k, v, bias, seed, float(scale), bool(causal), rate,
                  "xla")


# ---------------------------------------------------------------------------
# rotary position embeddings (RoPE) — Llama-family models
# ---------------------------------------------------------------------------

@op("rope")
def rope(x, *, base=10000.0, position_offset=0):
    """Apply rotary position embeddings to (B, H, L, D) q/k tensors
    (TPU-native addition, no reference analog — the positional mechanism
    of the Llama family, BASELINE config 5).

    Rotates consecutive (even, odd) feature pairs by position-dependent
    angles: theta_i = pos / base^(2i/D).  ``position_offset`` supports
    KV-cache decode: a scalar offsets every row uniformly (queries at
    absolute positions offset..offset+L); a (B,) vector gives each
    batch row its own absolute depth (the slot-pool serving step, where
    every row is an independent sequence at its own position)."""
    B, H, L, D = x.shape
    half = D // 2
    inv_freq = 1.0 / (base ** (
        jnp.arange(0, half, dtype=jnp.float32) * 2.0 / D))
    off = jnp.asarray(position_offset, dtype=jnp.float32)
    pos = jnp.arange(L, dtype=jnp.float32) + off[..., None]  # (L,)|(B,L)
    angles = pos[..., None] * inv_freq              # (L,half)|(B,L,half)
    cos = jnp.expand_dims(jnp.cos(angles), -3)      # (1,L,h)|(B,1,L,h)
    sin = jnp.expand_dims(jnp.sin(angles), -3)
    x32 = x.astype(jnp.float32)
    x1 = x32[..., 0::2]
    x2 = x32[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(B, H, L, D)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# ring attention: sequence parallelism over a mesh axis
# ---------------------------------------------------------------------------

def _ring_attn_local(q, k, v, scale, causal, axis, n_shards):
    """Runs inside shard_map: q/k/v are the LOCAL sequence shards
    (B, H, L/n, D).  K/V rotate around the ring; each step folds one
    remote block into the online softmax."""
    my = lax.axis_index(axis)
    Lloc = q.shape[2]
    q32 = q.astype(jnp.float32)
    qpos = (my * Lloc + lax.broadcasted_iota(
        jnp.int32, (Lloc, 1), 0))[None, None]       # (1,1,Lloc,1)

    def step(carry, i):
        kcur, vcur, m, l, acc = carry
        src = (my - i) % n_shards                   # whose shard we hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       kcur.astype(jnp.float32)) * scale
        if causal:
            kpos = (src * Lloc + lax.broadcasted_iota(
                jnp.int32, (1, Lloc), 1))[None, None]
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vcur.astype(jnp.float32))
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_next = lax.ppermute(kcur, axis, perm)
        v_next = lax.ppermute(vcur, axis, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    B, H, _, D = q.shape
    m0 = jnp.full((B, H, Lloc, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lloc, 1), jnp.float32)
    a0 = jnp.zeros((B, H, Lloc, D), jnp.float32)
    (kf, vf, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, a0), jnp.arange(n_shards))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


@op("ring_attention", differentiable=True)
def ring_attention(q, k, v, *, scale: Optional[float] = None,
                   causal: bool = False, axis: str = "sp",
                   mesh=None):
    """Sequence-parallel attention: inputs sharded over ``axis`` on the seq
    dim; communication is ``ppermute`` around the ring (ICI-neighbor
    traffic only, the canonical long-context pattern)."""
    from .._jax_compat import NO_CHECK, shard_map
    from ..parallel.mesh import default_mesh, local_mesh_axes, P
    from jax.sharding import NamedSharding

    mesh = mesh or default_mesh()
    n = local_mesh_axes(mesh)[axis]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    seq_sharding = NamedSharding(mesh, P(None, None, axis, None))
    q = jax.device_put(q, seq_sharding)
    k = jax.device_put(k, seq_sharding)
    v = jax.device_put(v, seq_sharding)
    fn = shard_map(
        functools.partial(_ring_attn_local, scale=float(scale),
                          causal=bool(causal), axis=axis, n_shards=n),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
        **NO_CHECK)
    return fn(q, k, v)
