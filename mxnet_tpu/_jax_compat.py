"""Shims over jax API renames so one codebase spans jax 0.4.x ↔ 0.6+.

Every rename is detected ONCE here; callers import the resolved symbol
instead of re-probing (the next jax rename is a one-file fix):

- ``shard_map``: top-level ``jax.shard_map`` (>= 0.6) vs
  ``jax.experimental.shard_map.shard_map`` (0.4.x) — same signature.
- ``NO_CHECK``: kwargs disabling shard_map's static replication checker
  (``check_vma=False`` >= 0.6, ``check_rep=False`` 0.4.x).  On 0.4.x the
  checker also predates the ``pvary``/``pcast`` varying marks, so code
  relying on those must pass NO_CHECK unconditionally there.
- ``typeof``: ``jax.typeof`` (>= 0.6) vs ``jax.core.get_aval`` — the
  abstract value (shape/dtype) of an array.
- ``compiler_params``: ``pltpu.CompilerParams`` (>= 0.6) vs
  ``pltpu.TPUCompilerParams`` — same fields, renamed class.
"""
from __future__ import annotations

import inspect

import jax

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

NO_CHECK = {"check_vma": False} \
    if "check_vma" in inspect.signature(shard_map).parameters \
    else {"check_rep": False}


def typeof(x):
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    return jax.core.get_aval(x)


def compiler_params(pltpu, **kw):
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)
