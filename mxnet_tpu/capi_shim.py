"""Python side of the flat C ABI (``native/mxtpu_c_api.cc``).

The C library embeds CPython and forwards each ``MXPred*`` call here; this
module keeps the handle table and does the numpy marshalling so the C
layer stays a thin ABI shim (SURVEY.md §3.1 "C API" row — the reference's
``c_predict_api.cc`` standalone inference ABI).

All functions use only plain types (int handles, bytes, tuples) so the C
caller needs nothing beyond the stable CPython object protocol.
"""
from __future__ import annotations

import threading

import numpy as onp

_lock = threading.Lock()
_handles: dict = {}
_next_id = [1]


def _unpack_shapes(keys, indptr, shape_data) -> dict:
    """CSR-packed (keys, indptr, dims) -> {name: shape} (the packing every
    reference shape-taking C call uses)."""
    return {key: tuple(int(d) for d in shape_data[indptr[i]:indptr[i + 1]])
            for i, key in enumerate(keys)}


def create(symbol_file: str, param_file: str, keys, indptr, shape_data,
           dev_type: int = 1, dev_id: int = 0) -> int:
    """MXPredCreate: keys + CSR-packed input shapes -> handle id."""
    from .predictor import Predictor

    shapes = _unpack_shapes(keys, indptr, shape_data)
    pred = Predictor(symbol_file, param_file or None, shapes)
    with _lock:
        h = _next_id[0]
        _next_id[0] += 1
        _handles[h] = {"pred": pred, "outputs": []}
    return h


def set_input(h: int, name: str, buf: bytes) -> None:
    entry = _handles[h]
    pred = entry["pred"]
    shape = pred._input_shapes[name]
    arr = onp.frombuffer(buf, dtype=onp.float32).reshape(shape)
    pred.set_input(name, arr)


def forward(h: int) -> None:
    entry = _handles[h]
    entry["pred"].run()
    entry["outputs"] = [
        onp.ascontiguousarray(
            onp.asarray(entry["pred"].get_output(i).asnumpy(),
                        dtype=onp.float32))
        for i in range(entry["pred"].num_outputs)]


def num_outputs(h: int) -> int:
    return len(_handles[h]["outputs"])


def output_shape(h: int, index: int) -> tuple:
    return tuple(int(d) for d in _handles[h]["outputs"][index].shape)


def output_bytes(h: int, index: int) -> bytes:
    return _handles[h]["outputs"][index].tobytes()


def free(h: int) -> None:
    with _lock:
        _handles.pop(h, None)


def version() -> int:
    return 10900  # parity: reports the MXNet 1.9 line


# ======================================================================= #
# training ABI (VERDICT r3 item 5): NDArray / Symbol / Executor handles.
# Reference surface: src/c_api/c_api.cc MXNDArray* / MXSymbol* /
# MXExecutor* (SURVEY.md §3.1 "C API" row).  float32 subset — the
# training loop a C host needs: create arrays, copy in/out, bind an
# executor, forward, backward, read grads, write updated weights.
# ======================================================================= #

_GRAD_REQ = {0: "null", 1: "write", 2: "write", 3: "add"}  # kNullOp..kAddTo


def _put(obj) -> int:
    with _lock:
        h = _next_id[0]
        _next_id[0] += 1
        _handles[h] = obj
    return h


def nd_create(shape) -> int:
    from . import ndarray as nd
    return _put({"nd": nd.zeros(tuple(int(d) for d in shape))})


# NDArray handles release through the same table as predictors/symbols
nd_free = free


def nd_sync_copy_from(h: int, buf: bytes) -> None:
    from . import ndarray as nd
    entry = _handles[h]
    arr = entry["nd"]
    data = onp.frombuffer(buf, dtype=onp.float32)
    if data.size != arr.size:
        raise ValueError(
            f"SyncCopyFromCPU: got {data.size} elements, ndarray has "
            f"{arr.size}")
    entry["nd"]._rebind(nd.array(data.reshape(arr.shape))._data)


def nd_sync_copy_to(h: int) -> bytes:
    return onp.ascontiguousarray(
        onp.asarray(_handles[h]["nd"].asnumpy(), dtype=onp.float32)
    ).tobytes()


def nd_get_shape(h: int) -> tuple:
    return tuple(int(d) for d in _handles[h]["nd"].shape)


def sym_create_from_file(fname: str) -> int:
    from .symbol import symbol as sym_mod
    return _put({"sym": sym_mod.load(fname)})


def sym_list_arguments(h: int) -> tuple:
    return tuple(_handles[h]["sym"].list_arguments())


def sym_infer_shape(h: int, keys, indptr, shape_data):
    """Returns (in_shapes, out_shapes, aux_shapes) as tuples of tuples,
    argument order = list_arguments().  Partial inputs are completed via
    the InferShape pass (reference semantics: parameter shapes are
    DEDUCED from the data shapes)."""
    sym = _handles[h]["sym"]
    shapes = _unpack_shapes(keys, indptr, shape_data)
    arg_names = sym.list_arguments()
    if any(nm not in shapes for nm in arg_names):
        from .symbol.symbol import infer_args
        shapes = infer_args(sym, **shapes)
    in_shapes, out_shapes, aux_shapes = sym.infer_shape(**shapes)
    return (tuple(map(tuple, in_shapes)), tuple(map(tuple, out_shapes)),
            tuple(map(tuple, aux_shapes)))


def executor_bind(sym_h: int, arg_handles, grad_handles, grad_reqs) -> int:
    sym = _handles[sym_h]["sym"]
    arg_names = sym.list_arguments()
    if len(arg_handles) != len(arg_names):
        raise ValueError(
            f"bind: {len(arg_names)} arguments expected "
            f"({arg_names}), got {len(arg_handles)} handles")
    args = {nm: _handles[ah]["nd"]
            for nm, ah in zip(arg_names, arg_handles)}
    # a write/add req with a null grad handle has nowhere to store the
    # gradient — downgrade to 'null' explicitly rather than leaving a
    # dangling write request for Symbol.bind to interpret
    req = {nm: (_GRAD_REQ.get(int(r), "null") if gh else "null")
           for nm, gh, r in zip(arg_names, grad_handles, grad_reqs)}
    args_grad = {nm: _handles[gh]["nd"]
                 for nm, gh in zip(arg_names, grad_handles)
                 if gh and req[nm] != "null"}
    exe = sym.bind(args=args, args_grad=args_grad, grad_req=req)
    return _put({"exec": exe, "outputs": []})


def executor_forward(h: int, is_train: int) -> int:
    entry = _handles[h]
    entry["outputs"] = entry["exec"].forward(is_train=bool(is_train))
    return len(entry["outputs"])


def executor_backward(h: int) -> None:
    _handles[h]["exec"].backward()


def executor_num_outputs(h: int) -> int:
    return len(_handles[h]["outputs"])


def executor_output(h: int, index: int) -> int:
    """Wrap output ``index`` as a NEW ndarray handle (caller frees)."""
    return _put({"nd": _handles[h]["outputs"][index]})


def nd_get_dtype(h: int) -> int:
    """MXNDArrayGetDType: the reference's dtype enum (shared table with
    the .params serializer)."""
    from .base import dtype_np_to_mx
    return int(dtype_np_to_mx(_handles[h]["nd"].dtype))


def nd_save(fname: str, handles, keys) -> None:
    """MXNDArraySave: write a .params file (bit-format shared with
    mx.nd.save) from C-held ndarray handles."""
    from .ndarray import serialization as ser
    arrays = [_handles[h]["nd"] for h in handles]
    if keys:
        ser.save(fname, dict(zip(keys, arrays)))
    else:
        ser.save(fname, list(arrays))


def nd_load(fname: str):
    """MXNDArrayLoad: returns (handles tuple, names tuple)."""
    from .ndarray import serialization as ser
    loaded = ser.load(fname)
    if isinstance(loaded, dict):
        names = tuple(loaded.keys())
        hs = tuple(_put({"nd": v}) for v in loaded.values())
    else:
        names = ()
        hs = tuple(_put({"nd": v}) for v in loaded)
    return hs, names


def sym_save_to_file(h: int, fname: str) -> None:
    """MXSymbolSaveToFile: the exported-json format."""
    _handles[h]["sym"].save(fname)


def op_list_names() -> tuple:
    """MXListAllOpNames: every registered op name + alias (the registry
    IS the dispatch table, SURVEY.md §3.1 C API row)."""
    from .ops.registry import OPS, _ALIASES
    return tuple(sorted(set(OPS) | set(_ALIASES)))


def op_exists(name: str) -> int:
    from .ops.registry import get_op
    try:
        get_op(name)
        return 1
    except Exception:
        return 0


def imperative_invoke(name: str, in_handles, out_handles, keys, vals):
    """MXImperativeInvoke: name-based eager op dispatch — THE per-op fast
    path every reference binding sits on (SURVEY.md §3.1 C API row,
    call stack §4.1).  Inputs are ndarray handles; attrs arrive as
    strings and parse the way the reference's dmlc::Parameter does
    (python-literal syntax — ints, floats, bools, tuples — else the raw
    string).  With a caller-supplied out handle the result rebinds that
    handle (reference in-place semantics: ``sgd_update(w, g, out=w)``
    updates w through the caller's existing handle); otherwise fresh
    handles are returned (caller frees via MXNDArrayFree)."""
    import ast

    from .ops.registry import get_op
    get_op(name)  # raises on unknown -> clean MXGetLastError surface
    import mxnet_tpu as mx
    fn = getattr(mx.nd, name, None)
    if fn is None or not callable(fn):
        raise ValueError(
            f"imperative invoke: op {name!r} is registered but has no "
            f"mx.nd wrapper")
    arrays = [_handles[h]["nd"] for h in in_handles]
    kwargs = {}
    for k, v in zip(keys, vals):
        try:
            kwargs[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            kwargs[k] = v
    outs = [_handles[h]["nd"] for h in out_handles]
    if len(outs) > 1:
        raise ValueError(
            "imperative invoke: at most one caller-supplied out handle "
            "(multi-output ops allocate their outputs)")
    res = fn(*arrays, out=outs[0] if outs else None, **kwargs)
    if outs:
        return tuple(out_handles)
    res = res if isinstance(res, (list, tuple)) else (res,)
    return tuple(_put({"nd": r}) for r in res)
