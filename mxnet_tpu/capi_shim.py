"""Python side of the flat C ABI (``native/mxtpu_c_api.cc``).

The C library embeds CPython and forwards each ``MXPred*`` call here; this
module keeps the handle table and does the numpy marshalling so the C
layer stays a thin ABI shim (SURVEY.md §3.1 "C API" row — the reference's
``c_predict_api.cc`` standalone inference ABI).

All functions use only plain types (int handles, bytes, tuples) so the C
caller needs nothing beyond the stable CPython object protocol.
"""
from __future__ import annotations

import threading

import numpy as onp

_lock = threading.Lock()
_handles: dict = {}
_next_id = [1]


def create(symbol_file: str, param_file: str, keys, indptr, shape_data,
           dev_type: int = 1, dev_id: int = 0) -> int:
    """MXPredCreate: keys + CSR-packed input shapes -> handle id."""
    from .predictor import Predictor

    shapes = {}
    for i, key in enumerate(keys):
        dims = tuple(int(d) for d in shape_data[indptr[i]:indptr[i + 1]])
        shapes[key] = dims
    pred = Predictor(symbol_file, param_file or None, shapes)
    with _lock:
        h = _next_id[0]
        _next_id[0] += 1
        _handles[h] = {"pred": pred, "outputs": []}
    return h


def set_input(h: int, name: str, buf: bytes) -> None:
    entry = _handles[h]
    pred = entry["pred"]
    shape = pred._input_shapes[name]
    arr = onp.frombuffer(buf, dtype=onp.float32).reshape(shape)
    pred.set_input(name, arr)


def forward(h: int) -> None:
    entry = _handles[h]
    entry["pred"].run()
    entry["outputs"] = [
        onp.ascontiguousarray(
            onp.asarray(entry["pred"].get_output(i).asnumpy(),
                        dtype=onp.float32))
        for i in range(entry["pred"].num_outputs)]


def num_outputs(h: int) -> int:
    return len(_handles[h]["outputs"])


def output_shape(h: int, index: int) -> tuple:
    return tuple(int(d) for d in _handles[h]["outputs"][index].shape)


def output_bytes(h: int, index: int) -> bytes:
    return _handles[h]["outputs"][index].tobytes()


def free(h: int) -> None:
    with _lock:
        _handles.pop(h, None)


def version() -> int:
    return 10900  # parity: reports the MXNet 1.9 line
