"""``mx.operator`` — Python custom operators.

Reference surface: ``python/mxnet/operator.py`` + ``src/operator/custom/``
(SURVEY.md §3.1 "Custom op (python)": a C++ op that calls back into Python
per invocation).  TPU-native: the callback IS Python already — a CustomOp
invocation runs eagerly on host-visible NDArrays and registers one tape
node whose backward calls the user's ``backward`` (same mechanics as
``autograd.Function``).  Inside a hybridized trace a CustomOp is opaque to
XLA, exactly as the reference's CustomOperator is opaque to the graph
engines.
"""
from __future__ import annotations

from .base import MXNetError
from . import autograd
from .ndarray.ndarray import NDArray

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_REGISTRY = {}


class CustomOp:
    """User forward/backward (reference ``mx.operator.CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Honor grad_req semantics (reference ``CustomOp.assign``)."""
        if req in ("null", 0):
            return
        if req in ("add", 3):
            dst._rebind(dst._data + (src._data if isinstance(src, NDArray)
                                     else src))
        else:  # write / inplace
            dst._rebind(src._data if isinstance(src, NDArray) else src)


class CustomOpProp:
    """Shape/type/creation metadata (reference ``CustomOpProp``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name):
    """``@mx.operator.register("myop")`` over a CustomOpProp subclass."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return deco


def get_all_registered():
    return dict(_REGISTRY)


def _invoke_custom(op_type, inputs, kwargs):
    """``mx.nd.Custom(*data, op_type=...)`` dispatch path."""
    if op_type not in _REGISTRY:
        raise MXNetError(f"custom op {op_type!r} is not registered")
    prop = _REGISTRY[op_type](**kwargs)
    in_shapes = [list(x.shape) for x in inputs]
    in_shapes, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    in_types = [x.dtype for x in inputs]
    _, out_types, _ = prop.infer_type(in_types)
    from . import ndarray as nd
    op = prop.create_operator(None, in_shapes, in_types)

    out_data = [nd.zeros(tuple(s), dtype=str(t))
                for s, t in zip(out_shapes, out_types)]
    aux = [nd.zeros(tuple(s)) for s in aux_shapes]

    class _Fn(autograd.Function):
        def forward(self, *xs):
            op.forward(is_train=autograd.is_training(),
                       req=["write"] * len(out_data), in_data=list(xs),
                       out_data=out_data, aux=aux)
            outs = tuple(o._data for o in out_data)
            return [NDArray(o) for o in outs] if len(outs) > 1 \
                else NDArray(outs[0])

        def backward(self, *ograds):
            in_grad = [nd.zeros(x.shape, dtype=str(x.dtype)) for x in inputs]
            op.backward(req=["write"] * len(inputs),
                        out_grad=list(ograds), in_data=list(inputs),
                        out_data=out_data, in_grad=in_grad, aux=aux)
            return tuple(in_grad)

    fn = _Fn()
    fn.__class__.__name__ = f"Custom_{op_type}"
    return fn(*inputs)
