"""``mx.profiler`` — profiling facade.

Reference surface: ``python/mxnet/profiler.py`` + ``src/profiler/``
(SURVEY.md §5.1): ``set_config(profile_all=..., filename=...)``,
``start/stop/pause/resume/dump``, per-op aggregate stats
(``dumps(reset)``), and user domains ``Task``/``Counter``/``Marker``/
``Scope``.

TPU-native: device-side tracing is ``jax.profiler`` (TensorBoard /
Perfetto trace of XLA ops on the TPU) — ``start/stop`` wrap
``jax.profiler.start_trace/stop_trace``; ``Task``/``Scope`` map onto
``jax.profiler.TraceAnnotation`` so user ranges appear in the device
timeline.  Host-side per-op aggregate timing (the reference's
``MXAggregateProfileStatsPrint`` table) is kept by a lightweight hook in
the op-dispatch path, enabled while profiling is on."""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict

import jax

__all__ = ["set_config", "profiler_set_config", "start", "stop", "pause",
           "resume", "dump", "dumps", "device_dumps", "set_state", "state",
           "Task", "Frame", "Counter", "Marker", "Scope", "TraceAnnotation"]

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": True,
    "continuous_dump": False,
}
_state = {"running": False, "trace_dir": None, "op_stats": None,
          "paused": False}


def set_config(**kwargs):
    """``mx.profiler.set_config(profile_all=True, filename='prof')`` —
    ``filename`` names the trace output directory (TensorBoard/Perfetto
    format rather than the reference's single chrome-tracing JSON).

    Unknown keys raise ``MXNetError`` naming the offender — a typoed
    ``profile_imperativ=`` must not silently configure nothing."""
    from .base import MXNetError

    unknown = sorted(set(kwargs) - set(_config))
    if unknown:
        raise MXNetError(
            f"profiler.set_config: unknown config key(s) {unknown}; "
            f"known keys: {sorted(_config)}")
    _config.update(kwargs)


profiler_set_config = set_config


class _OpStats:
    """Aggregate per-op host-dispatch stats (reference aggregate table)."""

    def __init__(self):
        self.times = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])

    def record(self, name, dt):
        t = self.times[name]
        t[0] += 1
        t[1] += dt
        t[2] = min(t[2], dt)
        t[3] = max(t[3], dt)

    def table(self):
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"
                 f"{'Min(ms)':>10}{'Max(ms)':>10}", "-" * 80]
        for name, (n, tot, mn, mx) in sorted(
                self.times.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{n:>8}{tot * 1e3:>12.3f}"
                         f"{mn * 1e3:>10.3f}{mx * 1e3:>10.3f}")
        return "\n".join(lines)


def _hook(name, dt):
    # under _lock: ``dumps(reset=True)`` swaps op_stats while dispatch
    # threads record — an unlocked read-then-record here could land a
    # row in the already-rendered stats object (a lost count)
    with _lock:
        st = _state["op_stats"]
        if st is not None:
            st.record(name, dt)


def start():
    """Start profiling: device trace + host op stats."""
    # wire the per-op hook into the dispatch path (ops/registry.invoke)
    import sys
    from .ops import registry as _registry
    _registry._profiler = sys.modules[__name__]
    with _lock:
        if _state["running"]:
            return
        trace_dir = _config["filename"]
        if trace_dir.endswith(".json"):
            trace_dir = trace_dir[:-5] + "_trace"
        os.makedirs(trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception:
            pass  # nested/unsupported backends: keep host stats only
        _state["running"] = True
        _state["trace_dir"] = trace_dir
        if _state["op_stats"] is None or not _state["paused"]:
            _state["op_stats"] = _OpStats()
        _state["paused"] = False


def stop():
    with _lock:
        if not _state["running"]:
            return
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _state["running"] = False


def pause(profile_process="worker"):
    """Suspend collection WITHOUT resetting accumulated stats (reference
    pause/resume semantics)."""
    with _lock:
        if not _state["running"]:
            return
        _state["paused"] = True
    stop()


def resume(profile_process="worker"):
    start()


def dump(finished=True, profile_process="worker"):
    """Write the aggregate table next to the trace dir (the device trace
    itself is already on disk in TensorBoard format)."""
    st = _state["op_stats"]
    if st is None:
        return
    out = {"traceEvents": [
        {"name": name, "ph": "X", "ts": 0, "dur": v[1] * 1e6,
         "pid": 0, "tid": 0, "args": {"calls": v[0]}}
        for name, v in st.times.items()]}
    fname = _config["filename"]
    if not fname.endswith(".json"):
        fname += ".json"
    with open(fname, "w") as f:
        json.dump(out, f)


def dumps(reset=False):
    """Return the aggregate stats table as a string (reference
    ``MXAggregateProfileStatsPrint``)."""
    with _lock:
        st = _state["op_stats"]
        s = st.table() if st else ""
        if reset and st:
            _state["op_stats"] = _OpStats()
    return s


def device_dumps(by="tf_op", peak_tflops=None, limit=30):
    """Per-XLA-op device-time table for the last ``start()``/``stop()``
    window — the reference's per-op aggregate, recovered *inside* fused
    jit steps by parsing the device trace (see ``profiler_xla``).

    ``by``: "tf_op" (jaxpr-level provenance), "name" (HLO op),
    "category" (convolution/fusion/copy/all-reduce...), or "source"."""
    from . import profiler_xla
    if by not in ("tf_op", "name", "category", "source"):
        raise ValueError(f"by={by!r}: expected one of "
                         "'tf_op', 'name', 'category', 'source'")
    td = _state["trace_dir"]
    if not td:
        return ""
    try:
        rows = profiler_xla.aggregate(profiler_xla.parse_trace(td), by=by)
    except Exception:
        return ""  # missing/truncated/in-flight trace: best-effort dump
    return profiler_xla.format_table(rows, peak_tflops=peak_tflops,
                                     limit=limit)


def set_state(state="stop", profile_process="worker"):
    if state in ("run", "start"):
        start()
    else:
        stop()


def state():
    return "run" if _state["running"] else "stop"


# --------------------------------------------------------------------------- #
# user annotation domains
# --------------------------------------------------------------------------- #

TraceAnnotation = jax.profiler.TraceAnnotation


class Scope:
    """``with mx.profiler.Scope('name'):`` — device-timeline annotation."""

    def __init__(self, name="<unk>"):
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *a):
        self._ann.__exit__(*a)


class Task:
    """Named task with explicit start/stop (reference ``ProfileTask``)."""

    def __init__(self, domain=None, name="task"):
        self.name = getattr(domain, "name", "") + name \
            if domain is not None else name
        self._ann = None

    def start(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None


Frame = Task


class Counter:
    """Numeric counter (reference ``ProfileCounter``), delegated to the
    process-wide telemetry registry: the value lives in a
    ``profiler_counter{counter=}`` gauge (counters may decrement, so the
    backing instrument is a gauge), visible in ``mx.telemetry.
    snapshot()`` / ``render_prometheus()`` next to the runtime's own
    metrics.  The reference API (``set_value``/``increment``/
    ``decrement``/``+=``) is unchanged."""

    def __init__(self, domain=None, name="counter", value=None):
        from . import telemetry
        self.name = getattr(domain, "name", "") + name \
            if domain is not None else name
        # same (domain+)name = same backing gauge, so two Counter
        # objects over one name share a value (registry identity); a
        # fresh gauge starts at 0 and an existing one is NOT reset here
        self._gauge = telemetry.gauge("profiler_counter",
                                      counter=self.name)
        if value is not None:
            self.set_value(value)

    @property
    def value(self):
        return self._gauge.value

    @value.setter
    def value(self, v):
        # the reference API allowed plain ``c.value = n`` assignment
        self._gauge.set(v)

    def set_value(self, value):
        self._gauge.set(value)

    def increment(self, delta=1):
        self._gauge.add(delta)

    def decrement(self, delta=1):
        self._gauge.add(-delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant event (reference ``ProfileMarker``), delegated to the
    telemetry event log (kind ``marker``) AND the device timeline."""

    def __init__(self, domain=None, name="marker"):
        self.name = getattr(domain, "name", "") + name \
            if domain is not None else name

    def mark(self, scope="process"):
        from . import telemetry
        telemetry.emit("marker", name=self.name, scope=scope)
        with jax.profiler.TraceAnnotation(f"marker:{self.name}"):
            pass


class Domain:
    def __init__(self, name):
        self.name = name


atexit.register(stop)
