"""KVStore implementation (see package docstring for the design map)."""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..telemetry.faults import fault_point

__all__ = ["KVStore", "create"]


def _one_device_per_process():
    byproc = {}
    for d in jax.devices():
        byproc.setdefault(d.process_index, d)
    return [byproc[k] for k in sorted(byproc)]


_REDUCE_CACHE: dict = {}


def _cross_process_sum(x):
    """TRUE reduce across processes: one compiled XLA AllReduce over the
    DCN process mesh (r3 upgrade, VERDICT item 9 — the r2 path was
    ``process_allgather`` + host sum: N× wire traffic plus a host hop).

    Requires ``jax.distributed.initialize`` to have run (see
    ``mxnet_tpu.parallel.init_distributed`` / ``tools/launch.py``)."""
    import numpy as onp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = _one_device_per_process()
    n = len(devs)
    x = jnp.asarray(x)
    if n == 1:
        return x
    mesh = Mesh(onp.asarray(devs), ("p",))
    # keep x on device: host_local_array_to_global_array accepts
    # jax.Arrays, so no D2H round trip before the collective
    glob = multihost_utils.host_local_array_to_global_array(
        x[None], mesh, PartitionSpec("p"))
    key = (n, tuple(x.shape), str(x.dtype))
    fn = _REDUCE_CACHE.get(key)
    if fn is None:
        fn = jax.jit(lambda a: jnp.sum(a, axis=0),
                     out_shardings=NamedSharding(mesh, PartitionSpec()))
        _REDUCE_CACHE[key] = fn
    return fn(glob).addressable_data(0)

def _put_like(data, o):
    """Cast + place ``data`` on the out array's device (the reference's
    broadcast-back-to-each-ctx after a reduce)."""
    data = jnp.asarray(data, o._data.dtype)
    try:
        tgt = list(o._data.devices())[0]
        if list(data.devices())[0] != tgt:
            data = jax.device_put(data, tgt)
    except Exception:
        pass
    return data


_KNOWN_TYPES = ("local", "device", "nccl", "tpu", "dist_sync", "dist_async",
                "dist_device_sync", "dist")


def create(name="local"):
    if name not in _KNOWN_TYPES:
        raise MXNetError(f"unknown kvstore type {name}")
    if name == "dist_async":
        import warnings
        warnings.warn(
            "kvstore 'dist_async' runs with SYNCHRONOUS semantics on TPU "
            "(async parameter serving is anti-idiomatic under XLA "
            "collectives; see PARITY.md). Updates are applied at barrier "
            "points, not per-worker-push.", UserWarning, stacklevel=2)
    return KVStore(name)


class KVStore:
    """Single-process store; multi-host coordination builds on
    ``jax.distributed`` (mxnet_tpu.parallel.init_distributed)."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store: dict = {}
        self._updater = None
        self._optimizer = None
        self._opt_states: dict = {}
        self._compression_params = None
        self._compression = None

    # -- identity ---------------------------------------------------------- #
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        try:
            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self):
        try:
            return jax.process_count()
        except Exception:
            return 1

    # -- core API ---------------------------------------------------------- #
    def init(self, key, value):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        key = str(key)
        if key in self._store:
            return
        v = value[0] if isinstance(value, (list, tuple)) else value
        self._store[key] = NDArray(jnp.asarray(v._data))
        if self._optimizer is not None:
            self._opt_states[key] = \
                self._optimizer.create_state_multi_precision(
                    key, self._store[key])

    def _merge_local(self, value, key=None):
        """Sum a per-device value list (reference: CommDevice tree-reduce /
        NCCL ring; here one fused add chain — on one chip it's identity),
        with optional 2-bit compression + error feedback on the result."""
        if not isinstance(value, (list, tuple)):
            acc = value._data
        elif len(value) == 1:
            acc = value[0]._data
        else:
            # per-device values: gather to the first value's device, then
            # one add chain (reference CommDevice reduce-at-root)
            acc = value[0]._data
            try:
                root = list(acc.devices())[0]
            except Exception:
                root = None
            for v in value[1:]:
                rhs = v._data
                if root is not None:
                    try:
                        if list(rhs.devices())[0] != root:
                            rhs = jax.device_put(rhs, root)
                    except Exception:
                        pass
                acc = acc + rhs
        if self._compression is not None and key is not None:
            acc = self._compression.compress(key, acc)
        return acc

    def _merge(self, value, key=None):
        """Local merge, then — for ``dist_*`` stores — ONE AllReduce
        across processes (the ps-lite hop → DCN collective,
        SURVEY.md §5.8)."""
        acc = self._merge_local(value, key)
        if self._kind.startswith("dist") and self.num_workers > 1:
            acc = _cross_process_sum(acc)
        return acc

    def _reduce_bucketed(self, keys, merged):
        """Coalesce many per-key wire values into ONE flat AllReduce per
        dtype (reference: ``MXNET_KVSTORE_BIGARRAY_BOUND`` batches small
        keys across server shards; VERDICT r2 item 9).  Returns the
        reduced per-key arrays."""
        if not (self._kind.startswith("dist") and self.num_workers > 1):
            return merged
        by_dtype: dict = {}
        for i, m in enumerate(merged):
            by_dtype.setdefault(str(m.dtype), []).append(i)
        out = list(merged)
        for _dt, idxs in by_dtype.items():
            flat = jnp.concatenate([merged[i].reshape(-1) for i in idxs])
            red = _cross_process_sum(flat)
            off = 0
            for i in idxs:
                n = merged[i].size
                out[i] = red[off:off + n].reshape(merged[i].shape)
                off += n
        return out

    def push(self, key, value, priority=0):
        fault_point("kvstore.push", store=self._kind)
        if isinstance(key, (list, tuple)):
            if self._optimizer is not None:
                # optimizer-on-server, whole push wave at once: merge
                # every key's grads, then ONE fused multi-tensor apply
                # (O(#groups) jitted dispatches — the server-side analog
                # of the reference's aggregated multi_sgd_update)
                keys = [str(k) for k in key]
                for k in keys:
                    if k not in self._store:
                        raise MXNetError(f"kvstore key {k} not initialized")
                # local merge per key, then ONE flat cross-process
                # AllReduce per dtype for the whole wave (bucketing —
                # same wire coalescing the pure-allreduce pushpull uses)
                merged = [NDArray(m) for m in self._reduce_bucketed(
                    keys, [self._merge_local(v, k)
                           for k, v in zip(keys, value)])]
                new_states = self._optimizer.multi_update(
                    keys, [self._store[k] for k in keys], merged,
                    [self._opt_states[k] for k in keys])
                for k, ns in zip(keys, new_states):
                    self._opt_states[k] = ns
                return
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        key = str(key)
        if key not in self._store:
            raise MXNetError(f"kvstore key {key} not initialized")
        merged = self._merge(value, key)
        if self._optimizer is not None:
            # optimizer-on-server semantics (KVStoreDistServer)
            w = self._store[key]
            self._opt_states[key] = self._optimizer.multi_update(
                [key], [w], [NDArray(merged)], [self._opt_states[key]])[0]
        elif self._updater is not None:
            self._updater(key, NDArray(merged), self._store[key])
        else:
            self._store[key]._rebind(self._store[key]._data + merged)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        fault_point("kvstore.pull", store=self._kind)
        if isinstance(key, (list, tuple)) and isinstance(out, (list, tuple)) \
                and len(key) == len(out) and isinstance(key[0], (str, int)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        key = str(key)
        if key not in self._store:
            raise MXNetError(f"kvstore key {key} not initialized")
        src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            o._rebind(_put_like(src._data, o))

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference ``MXKVStorePushPull``).  With no
        updater this is a pure allreduce: out = sum(values).  A key LIST
        on a pure-allreduce ``dist_*`` store is coalesced into one flat
        AllReduce per dtype (bucketing — one wire collective per push
        wave instead of one per parameter)."""
        fault_point("kvstore.pushpull", store=self._kind)
        if isinstance(key, (list, tuple)) and not isinstance(key, str):
            vals = value
            outs = out if out is not None else [None] * len(key)
            if (self._kind.startswith("dist") and self.num_workers > 1
                    and self._optimizer is None
                    and self._updater is None):
                merged = [self._merge_local(v, str(k))
                          for k, v in zip(key, vals)]
                reduced = self._reduce_bucketed(
                    [str(k) for k in key], merged)
                for k, r, o in zip(key, reduced, outs):
                    k = str(k)
                    if o is None:
                        if k not in self._store:
                            raise MXNetError(
                                f"kvstore key {k} not initialized")
                        self._store[k]._rebind(r)
                    else:
                        os_ = o if isinstance(o, (list, tuple)) else [o]
                        for oo in os_:
                            oo._rebind(_put_like(r, oo))
                return
            for k, v, o in zip(key, vals, outs):
                self.pushpull(k, v, o, priority)
            return
        key = str(key)
        if self._optimizer is not None or self._updater is not None:
            self.push(key, value, priority)
            if out is not None:
                self.pull(key, out, priority)
            return
        # pure allreduce path (Trainer update_on_kvstore=False)
        merged = self._merge(value, key)
        if out is None:
            if key not in self._store:
                raise MXNetError(f"kvstore key {key} not initialized")
            self._store[key]._rebind(merged)
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            o._rebind(_put_like(merged, o))

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Reference ``PullRowSparse``: pull only touched rows.  Dense
        emulation documented in SURVEY.md §3.3: gather the requested rows."""
        if row_ids is None:
            return self.pull(key, out, priority)
        key = str(key)
        src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for o, r in zip(outs, rids):
            rows = jnp.take(src._data, r._data.astype(jnp.int32), axis=0)
            full = jnp.zeros_like(src._data)
            full = full.at[r._data.astype(jnp.int32)].set(rows)
            o._rebind(jnp.asarray(full, o._data.dtype))

    # -- updater / optimizer ----------------------------------------------- #
    def set_updater(self, updater):
        """updater(key, recv, stored) — local update fn (reference
        ``KVStore::set_updater``)."""
        self._updater = updater

    def set_optimizer(self, optimizer):
        """Run the optimizer inside the store at push time (reference: the
        worker pickles the optimizer to the PS server via
        ``SendCommandToServers``; here the 'server' is this process)."""
        self._optimizer = optimizer
        for key, w in self._store.items():
            self._opt_states[key] = \
                optimizer.create_state_multi_precision(key, w)

    @property
    def is_capable(self):
        return {"optimizer": True}

    def save_optimizer_states(self, fname, dump_optimizer=False):
        payload = {"states": {k: jax.tree.map(
            lambda a: jax.device_get(a), v)
            for k, v in self._opt_states.items()}}
        if dump_optimizer:
            payload["optimizer"] = self._optimizer
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        self._opt_states = payload["states"]
        if "optimizer" in payload:
            self._optimizer = payload["optimizer"]

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit gradient compression with error-feedback residual
        (reference ``GradientCompression``; SURVEY.md §3.1 KVStore row)."""
        from .compression import GradientCompression
        self._compression_params = compression_params
        params = dict(compression_params or {})
        self._compression = GradientCompression(
            type=params.get("type", "2bit"),
            threshold=float(params.get("threshold", 0.5)))

    def barrier(self):
        """Wait for local work, then sync the process group — through
        ``parallel.mesh.barrier``, so ``MXNET_BARRIER_TIMEOUT`` bounds
        the wait and a dead peer rank surfaces as a clean error
        instead of an indefinite hang in the collective."""
        from ..ndarray.ndarray import waitall
        waitall()
        if self._kind.startswith("dist") and self.num_workers > 1:
            from ..parallel.mesh import barrier as mesh_barrier
            mesh_barrier("kvstore_barrier")

    def _wait(self, keys):
        for k in (keys if isinstance(keys, (list, tuple)) else [keys]):
            self._store[str(k)].wait_to_read()
