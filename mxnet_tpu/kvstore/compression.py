"""Gradient compression (reference ``GradientCompression`` in
``src/kvstore/gradient_compression.cc``; SURVEY.md §3.1 KVStore row:
"2-bit with error-feedback residual").

2-bit scheme: each gradient element quantizes to {-threshold, 0,
+threshold}; the quantization error is kept in a per-key residual and added
to the next gradient (error feedback).  On TPU the quantize/dequantize pair
compiles to one fused XLA kernel; the wire benefit applies on the DCN hop
(SURVEY.md §3.3 "int8/bf16 compression before DCN allreduce").
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):  # noqa: A002
        if type not in ("2bit", "1bit"):
            raise MXNetError(f"unsupported compression type {type!r}")
        self.type = type
        self.threshold = float(threshold)
        if self.type == "2bit" and self.threshold <= 0:
            raise MXNetError("2bit compression needs threshold > 0")
        self._residual = {}

    def compress(self, key, grad):
        """→ quantized gradient (same shape, values in {-t, 0, +t} for 2bit
        or {-t, +t} for 1bit); residual updated with the quantization
        error."""
        r = self._residual.get(key)
        g = grad + r if r is not None else grad
        t = self.threshold
        if self.type == "2bit":
            q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0))
        else:  # 1bit: sign * threshold
            q = jnp.where(g >= 0, t, -t)
        self._residual[key] = g - q
        return q.astype(grad.dtype)

    def decompress(self, key, q):
        return q  # values are already in gradient units

    def reset(self):
        self._residual.clear()
