"""KVStore — the parameter synchronization facade.

Reference surface: ``src/kvstore/`` + ``python/mxnet/kvstore/`` (SURVEY.md
§3.1 "KVStore family", §5.8): uniform Init/Push/Pull/PushPull over arrays
keyed by int/str; ``local`` (CPU merge), ``device`` (GPU P2P trees),
``nccl`` (ring allreduce), ``dist_sync``/``dist_async`` (parameter server
with server-side optimizer).

TPU-native redesign (SURVEY.md §7 "KVStore"): on TPU the gradient
all-reduce is an XLA collective that GSPMD inserts *inside* the compiled
step (riding ICI), so the single-process kvstore ('local'/'device'/'nccl'/
'tpu') is a thin aggregation facade: push sums the per-device values (one
engine-free jnp.add chain — or nothing when there is one chip), pull
broadcasts.  ``dist_sync`` maps to a multi-host mesh over DCN via
``jax.distributed`` (see mxnet_tpu.parallel); the optimizer-on-server
semantics are preserved by running the updater at push time exactly like
``KVStoreDistServer::DataHandleEx``.  ``dist_async`` is accepted and
documented as executing synchronously (async PS is anti-idiomatic on TPU,
SURVEY.md §3.3).
"""
from .kvstore import KVStore, create

__all__ = ["KVStore", "create"]
