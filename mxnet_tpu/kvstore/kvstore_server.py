"""KVStore server role (reference ``python/mxnet/kvstore/kvstore_server.py``;
SURVEY.md §4.4: server processes run an event loop applying pushes and
serving pulls).

TPU-native reality: there IS no separate server process — the parameter
server collapses into XLA collectives over the device mesh (SURVEY.md §5.8),
so the reference's worker/server/scheduler roles map onto the single
``jax.distributed`` process group.  This module keeps the reference's import
surface and launch protocol working:

The optimizer-on-server update itself lives in ``KVStore.push``
(``kvstore.py``): a whole push wave applies as ONE fused
``Optimizer.multi_update`` per parameter group — the TPU analog of the
reference server's aggregated ``multi_sgd_update`` batching
(``MXNET_FUSED_OPTIMIZER=0`` restores the per-key loop).

- ``DMLC_ROLE=worker`` (or unset): no-op, training proceeds.
- ``DMLC_ROLE=server`` / ``scheduler``: the process joins the
  ``jax.distributed`` group (so barriers and coordination work for code
  that still launches dedicated server ranks) and then parks in the
  reference server loop shape until the job ends.
"""
from __future__ import annotations

import logging
import os
import time


class KVStoreServer:
    """API-compatible stand-in for the reference ``KVStoreServer``."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handlers = {}

    def run(self):
        logging.info(
            "mxnet_tpu kvstore server role: parameter-server duties are "
            "subsumed by XLA collectives; this process idles for protocol "
            "compatibility. Launch workers only (tools/launch.py -s 0) to "
            "avoid paying for this process.")
        while os.environ.get("DMLC_ROLE") in ("server", "scheduler"):
            time.sleep(60)


def _init_kvstore_server_module():
    """Reference import hook: start the server loop when this process was
    launched in a server role."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role in ("server", "scheduler"):
        from . import create
        server = KVStoreServer(create("dist_sync"))
        server.run()
