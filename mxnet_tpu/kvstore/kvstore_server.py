"""KVStore server role (reference ``python/mxnet/kvstore/kvstore_server.py``;
SURVEY.md §4.4: server processes run an event loop applying pushes and
serving pulls).

TPU-native reality: there IS no separate server process — the parameter
server collapses into XLA collectives over the device mesh (SURVEY.md §5.8),
so the reference's worker/server/scheduler roles map onto the single
``jax.distributed`` process group.  This module keeps the reference's import
surface and launch protocol working:

The optimizer-on-server update itself lives in ``KVStore.push``
(``kvstore.py``): a whole push wave applies as ONE fused
``Optimizer.multi_update`` per parameter group — the TPU analog of the
reference server's aggregated ``multi_sgd_update`` batching
(``MXNET_FUSED_OPTIMIZER=0`` restores the per-key loop).

- ``DMLC_ROLE=worker`` (or unset): no-op, training proceeds.
- ``DMLC_ROLE=server`` / ``scheduler``: the process joins the
  ``jax.distributed`` group (so barriers and coordination work for code
  that still launches dedicated server ranks) and then runs the
  reference server loop shape until the job ends.

Fault tolerance (ISSUE 13): the loop is a real request loop now, and a
request that fails is REPORTED TO THE REQUESTING RANK as an error reply
(``KVStoreServer.submit(...).wait()`` raises a clean ``MXNetError``
naming the command) instead of killing the server — a dead server looks
like a hang to every worker blocked on its next pull, which is the one
failure mode this layer must never manufacture.  The parked server rank
also heartbeats (``mxnet_tpu.parallel.heartbeat``) so a supervised
launch sees it as alive, and ``stop()`` ends the loop promptly.
"""
from __future__ import annotations

import logging
import os
import queue
import threading

from ..base import MXNetError


class ServerReply:
    """The requesting rank's handle on one server request: ``wait()``
    blocks for the result and RAISES the server-side failure as a
    clean ``MXNetError`` (the reference's ps-lite response message,
    collapsed to in-process form)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._result = None
        self._error = None

    def _resolve(self, result):
        # first outcome wins: the submit-vs-stop race can legitimately
        # settle one reply from two threads (server loop + the
        # requester's own stopped-check backstop)
        with self._lock:
            if self._done.is_set():
                return
            self._result = result
            self._done.set()

    def _reject(self, error):
        with self._lock:
            if self._done.is_set():
                return
            self._error = error
            self._done.set()

    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise MXNetError(
                f"kvstore server reply not ready within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class KVStoreServer:
    """API-compatible stand-in for the reference ``KVStoreServer``,
    with a real per-request loop: built-in ``init``/``push``/``pull``/
    ``barrier`` commands against the owned store, plus custom
    ``handlers[command] = fn(server, payload)`` (the reference's
    ``SendCommandToServers`` controller hook)."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handlers = {}
        self._requests = queue.Queue()
        self._stop = threading.Event()

    # -- requesting-rank side ------------------------------------------- #
    def submit(self, command, payload=None):
        """Enqueue one request; returns its :class:`ServerReply`."""
        if self._stop.is_set():
            raise MXNetError("kvstore server is stopped")
        reply = ServerReply()
        self._requests.put((command, payload, reply))
        if self._stop.is_set():
            # stop() raced the put: the run() shutdown drain may have
            # already emptied the queue before our entry landed, so
            # nobody else will ever settle this reply — reject it HERE
            # (first-outcome-wins makes a double settle harmless) so
            # reply.wait() can never strand the requesting rank
            reply._reject(MXNetError("kvstore server is stopped"))
        return reply

    def stop(self):
        """End :meth:`run` promptly (clean shutdown — in-queue requests
        are failed with a server-stopped error, not dropped; drained
        HERE too, so a stop() with no active run() loop — the
        serve_one-driven embedding case — strands nothing)."""
        self._stop.set()
        self._drain_reject()

    def _drain_reject(self):
        """Fail (not strand) everything queued; first-outcome-wins
        replies make a concurrent run()-finally double-drain harmless."""
        while True:
            try:
                _c, _p, reply = self._requests.get_nowait()
            except queue.Empty:
                return
            reply._reject(MXNetError("kvstore server is stopped"))

    # -- server side ----------------------------------------------------- #
    def handle(self, command, payload):
        """Dispatch one request (custom handlers win over built-ins)."""
        fn = self.handlers.get(command)
        if fn is not None:
            return fn(self, payload)
        if command == "init":
            key, value = payload
            return self.kvstore.init(key, value)
        if command == "push":
            key, value = payload
            return self.kvstore.push(key, value)
        if command == "pull":
            key, out = payload
            self.kvstore.pull(key, out=out)
            return out
        if command == "barrier":
            return self.kvstore.barrier()
        raise MXNetError(f"kvstore server: unknown command {command!r} "
                         f"(handlers: {sorted(self.handlers)})")

    def serve_one(self, timeout=0.2):
        """Serve at most one queued request.  A handler exception is
        caught, reported on the request's reply (so the REQUESTING rank
        sees the error), counted in telemetry — and the loop lives on.
        Returns True when a request was served."""
        try:
            command, payload, reply = self._requests.get(timeout=timeout)
        except queue.Empty:
            return False
        if self._stop.is_set():
            reply._reject(MXNetError("kvstore server is stopped"))
            return True
        try:
            reply._resolve(self.handle(command, payload))
        except Exception as e:   # report, don't die: a dead server is
            from .. import telemetry   # a hang for every worker

            telemetry.emit("kvstore_error", command=str(command),
                           error=repr(e))
            telemetry.counter("kvstore_request_errors_total",
                              command=str(command)).inc()
            err = e if isinstance(e, MXNetError) else MXNetError(
                f"kvstore server: request {command!r} failed: {e!r}")
            reply._reject(err)
        return True

    def run(self, serve_any_role=False):
        """The server loop.  Honors the reference contract: with
        ``DMLC_ROLE`` unset or ``worker`` the loop exits immediately
        (no-op role) — pass ``serve_any_role=True`` to run the command
        loop regardless (embedding/test use).  However the loop exits,
        ``submit()`` is poisoned and the backlog failed, never
        stranded."""
        from ..parallel.heartbeat import start_heartbeat

        start_heartbeat()
        logging.info(
            "mxnet_tpu kvstore server role: parameter-server duties are "
            "subsumed by XLA collectives; this process serves the "
            "compat command loop. Launch workers only (tools/launch.py "
            "-s 0) to avoid paying for this process.")
        try:
            while not self._stop.is_set() and (
                    serve_any_role or
                    os.environ.get("DMLC_ROLE") in ("server",
                                                    "scheduler")):
                self.serve_one()
        finally:
            # however the loop exited (stop() OR a role-env change),
            # the server is gone: poison submit() first so a racing
            # request raises instead of enqueueing into a queue nobody
            # will ever serve, then fail (not strand) the backlog
            self._stop.set()
            self._drain_reject()


def _init_kvstore_server_module():
    """Reference import hook: start the server loop when this process was
    launched in a server role."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role in ("server", "scheduler"):
        from . import create
        server = KVStoreServer(create("dist_sync"))
        server.run()
