"""Structured runtime event log: compile/retrace events, serve request
spans, bench rows — one stream, one schema.

Every event is a flat dict ``{"ts": epoch_seconds, "kind": str, ...}``.
Events always land in a bounded in-process ring (queryable via
:func:`events`), and fan out to any attached sinks — the JSONL sink
(:func:`add_jsonl_sink`, or ``MXNET_TELEMETRY_JSONL=path`` to attach
one at first emit) writes one JSON object per line, the schema
``tools/telemetry_report.py`` summarizes.  ``MXNET_TELEMETRY=0``
disables emission entirely (the enabled check is one dict lookup).

Emission cost: one dict build + deque append under a lock; sinks run
outside the lock on the emitting thread.  A sink that raises is dropped
(with one warning) — a broken exporter must not take down serving.
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from collections import deque

__all__ = ["emit", "events", "clear_events", "add_sink", "remove_sink",
           "add_jsonl_sink", "JsonlSink", "telemetry_enabled"]

_lock = threading.Lock()
_ring = None            # created lazily: capacity from env
_sinks = []
_env_sink_checked = False


def telemetry_enabled():
    """``MXNET_TELEMETRY=0`` turns event emission and the compile watch
    off (read per call so tests can toggle it)."""
    return os.environ.get("MXNET_TELEMETRY", "1") != "0"


def _ring_capacity():
    raw = os.environ.get("MXNET_TELEMETRY_EVENTS", "4096")
    try:
        return max(int(raw), 0)
    except ValueError:
        return 4096


def _ensure_ring_locked():
    global _ring
    if _ring is None:
        _ring = deque(maxlen=_ring_capacity())


def _attach_env_sink():
    """One-time ``MXNET_TELEMETRY_JSONL`` auto-attach (first emit).
    The sink is opened OUTSIDE the lock; registration (and the checked
    flag) flips under it — a lost race closes the duplicate."""
    global _env_sink_checked
    path = os.environ.get("MXNET_TELEMETRY_JSONL")
    sink = None
    if path:
        try:
            sink = JsonlSink(path)
        except OSError as e:
            warnings.warn(
                f"MXNET_TELEMETRY_JSONL={path!r}: {e!r} — JSONL "
                "sink not attached")
    with _lock:
        if _env_sink_checked:
            lost_race = sink
            sink = None
        else:
            _env_sink_checked = True
            if sink is not None:
                _sinks.append(sink)
            lost_race = None
    if lost_race is not None:
        lost_race.close()


def _rank_tag():
    """The pod rank this process was launched as (``MXNET_WORKER_ID``,
    exported by ``tools/launch.py``), or None single-process.  Read
    from the environment per emit — one dict lookup, same cost
    discipline as :func:`telemetry_enabled` — so merged per-rank
    recordings (``telemetry_report --pod``) can attribute every event
    to its host without a jax import on the emit path."""
    raw = os.environ.get("MXNET_WORKER_ID")
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return raw


def emit(kind, **fields):
    """Record one event; returns the event dict (None when disabled).
    Pod runs add a ``rank`` field (see :func:`_rank_tag`); an explicit
    ``rank=`` kwarg wins."""
    if not telemetry_enabled():
        return None
    if not _env_sink_checked:
        _attach_env_sink()
    ev = {"ts": round(time.time(), 6), "kind": str(kind)}
    rank = _rank_tag()
    if rank is not None:
        ev["rank"] = rank
    ev.update(fields)
    with _lock:
        _ensure_ring_locked()
        _ring.append(ev)
        sinks = tuple(_sinks)
    for s in sinks:
        try:
            s(ev)
        except Exception as e:
            warnings.warn(f"telemetry sink {s!r} raised {e!r} — "
                          "sink dropped")
            remove_sink(s)
    return ev


def events(kind=None):
    """Snapshot of the in-process ring, oldest first, optionally
    filtered by ``kind``."""
    with _lock:
        snap = list(_ring) if _ring is not None else []
    if kind is None:
        return snap
    return [e for e in snap if e.get("kind") == kind]


def clear_events():
    """Drop the ring (capacity re-read from the environment) — test
    isolation helper.  Attached sinks stay attached."""
    global _ring
    with _lock:
        _ring = deque(maxlen=_ring_capacity())


def add_sink(sink):
    """Attach a callable ``sink(event_dict)``; returns it for
    :func:`remove_sink`."""
    with _lock:
        _ensure_ring_locked()
        _sinks.append(sink)
    return sink


def remove_sink(sink):
    with _lock:
        if sink in _sinks:
            _sinks.remove(sink)
    close = getattr(sink, "close", None)
    if callable(close):
        try:
            close()
        except OSError:
            pass


def _jsonable(o):
    item = getattr(o, "item", None)  # numpy/jax scalars
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(o)


class JsonlSink:
    """One JSON object per line, flushed per event (crash-safe streams
    beat buffered throughput for telemetry)."""

    def __init__(self, path):
        self._f = open(path, "a", encoding="utf-8")
        self._wlock = threading.Lock()

    def __call__(self, ev):
        line = json.dumps(ev, default=_jsonable)
        with self._wlock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self):
        with self._wlock:
            if not self._f.closed:
                self._f.close()

    def __repr__(self):
        name = getattr(self._f, "name", "?")
        return f"JsonlSink({name!r})"


def add_jsonl_sink(path):
    """Attach a :class:`JsonlSink` writing to ``path`` (append mode)."""
    return add_sink(JsonlSink(path))
