"""Deterministic fault injection (``MXNET_FAULT_INJECT``).

The recovery paths this repo promises — a supervised launch that turns
a dead rank into a clean nonzero exit, a serve scheduler whose death
fails every in-flight stream instead of hanging consumers, kvstore
requests that come back as errors — are exactly the paths ordinary
tests never execute.  This module makes them executable ON CPU, in
tier-1, deterministically: named injection sites are threaded through
the hot control paths (serve scheduler pump / admit / step dispatch,
kvstore push/pull, launch heartbeats), and an env spec arms them.

Spec grammar (comma-separated rules)::

    MXNET_FAULT_INJECT=site:kind:after_n[:arg][,site:kind:after_n...]

Each rule fires EXACTLY ONCE, on the ``after_n``-th hit of its site
(site hit counts are process-wide and shared by all rules).  Kinds:

- ``raise`` — raise ``MXNetError`` naming the site (the injected
  error every recovery path must surface, not swallow).
- ``delay`` — sleep ``arg`` seconds (default 0.05) and continue.
- ``hang``  — sleep ``arg`` seconds (default 3600): a wedged rank /
  dispatch, from the watchdogs' point of view.
- ``kill``  — ``os.kill(os.getpid(), arg or SIGKILL)``: hard process
  death, no cleanup, no exit handlers — what a preempted host or an
  OOM-killed rank looks like to its peers.

Zero overhead when unset: :func:`fault_point` is one ``os.environ``
dict lookup and a return — the same gate discipline as
``MXNET_TELEMETRY=0``.  When a rule fires, a ``fault_injected`` event
and a ``faults_injected_total{site,kind}`` counter are recorded first
(for ``raise``/``delay``/``hang``; ``kill`` dies too hard to flush),
so a recorded JSONL names every injected fault next to the failure it
caused (``tools/telemetry_report.py`` summarizes them).

Rank scoping: the spec is plain env, so per-rank faults in a
``tools/launch.py`` job are set by the rank itself (branch on
``MXNET_WORKER_ID`` before the first ``fault_point`` runs) — the
harness stays a pure site/count matcher.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from collections import namedtuple

from ..base import MXNetError

__all__ = ["fault_point", "parse_fault_spec", "reset_faults",
           "FaultRule"]

FaultRule = namedtuple("FaultRule", ("site", "kind", "after_n", "arg"))

_KINDS = ("raise", "delay", "hang", "kill")

_lock = threading.Lock()
_state = {"raw": None, "rules": ()}   # parsed spec, cached on the raw
_hits: dict = {}                      # site -> process-wide hit count
_fired: set = set()                   # rule indices already triggered


def parse_fault_spec(raw):
    """``site:kind:after_n[:arg]`` rules, comma-separated.  A malformed
    spec is a loud configuration error at the first armed site, not a
    silently inert chaos run."""
    rules = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) not in (3, 4):
            raise MXNetError(
                f"MXNET_FAULT_INJECT rule {part!r}: expected "
                "site:kind:after_n[:arg]")
        site, kind = fields[0], fields[1]
        if not site or kind not in _KINDS:
            raise MXNetError(
                f"MXNET_FAULT_INJECT rule {part!r}: kind must be one "
                f"of {'/'.join(_KINDS)}")
        try:
            after_n = int(fields[2])
            arg = float(fields[3]) if len(fields) == 4 else None
        except ValueError:
            raise MXNetError(
                f"MXNET_FAULT_INJECT rule {part!r}: after_n must be "
                "an integer (and arg a number)")
        if after_n < 1:
            raise MXNetError(
                f"MXNET_FAULT_INJECT rule {part!r}: after_n must be "
                ">= 1")
        rules.append(FaultRule(site, kind, after_n, arg))
    return tuple(rules)


def reset_faults():
    """Zero the site hit counts and re-arm every rule (test isolation:
    the spec cache is also dropped, so a monkeypatched env re-parses)."""
    with _lock:
        _hits.clear()
        _fired.clear()
        _state["raw"] = None
        _state["rules"] = ()


def _trigger(rule, context):
    from .events import emit
    from .registry import counter

    # context keys that would collide with emit()'s own parameter or
    # the event schema's reserved fields are prefixed, not fatal — a
    # sloppy call-site kwarg must not turn an armed fault into a
    # TypeError that masks the injection
    context = {(f"ctx_{k}" if k in ("kind", "ts", "site", "fault_kind",
                                    "after_n", "arg") else k): v
               for k, v in context.items()}
    emit("fault_injected", site=rule.site, fault_kind=rule.kind,
         after_n=rule.after_n, arg=rule.arg, **context)
    counter("faults_injected_total", site=rule.site,
            kind=rule.kind).inc()
    if rule.kind == "raise":
        raise MXNetError(
            f"injected fault at {rule.site} "
            f"(MXNET_FAULT_INJECT, hit {rule.after_n})")
    if rule.kind == "delay":
        time.sleep(rule.arg if rule.arg is not None else 0.05)
    elif rule.kind == "hang":
        time.sleep(rule.arg if rule.arg is not None else 3600.0)
    elif rule.kind == "kill":
        sig = int(rule.arg) if rule.arg is not None else signal.SIGKILL
        os.kill(os.getpid(), sig)
        time.sleep(5.0)   # SIGKILL delivery is not synchronous


def fault_point(site, **context):
    """One named injection site.  Free when ``MXNET_FAULT_INJECT`` is
    unset (one env dict lookup); otherwise counts the hit and fires any
    armed rule for ``site``.  ``context`` fields land on the
    ``fault_injected`` event."""
    raw = os.environ.get("MXNET_FAULT_INJECT")
    if not raw:
        if _state["raw"] is not None:
            # spec was unset: drop the cache, so re-arming the SAME
            # spec later re-fires instead of inheriting a stale
            # fired-set (a silently inert chaos run)
            reset_faults()
        return
    with _lock:
        if raw != _state["raw"]:
            _state["rules"] = parse_fault_spec(raw)
            _state["raw"] = raw
            _hits.clear()
            _fired.clear()
        n = _hits[site] = _hits.get(site, 0) + 1
        due = [(i, r) for i, r in enumerate(_state["rules"])
               if r.site == site and r.after_n == n and i not in _fired]
        for i, _ in due:
            _fired.add(i)
    for _, rule in due:
        _trigger(rule, context)
