"""Memory observability — the memory axis of ``mx.telemetry``
(ISSUE 10).

The reference MXNet ships a GPU memory profiler next to its operator
profiler; this module is that axis for the XLA runtime, in three
layers:

- **per-executable analysis** (:func:`memory_analysis`): XLA's
  buffer-assignment verdict for one compiled program — argument /
  output / temp (scratch) / generated-code bytes.  Under
  ``MXNET_TELEMETRY_MEM=1`` every ``compile`` event the
  :func:`~mxnet_tpu.telemetry.instrument_jit` watch emits carries these
  as ``mem_*`` fields (one extra AOT lower+compile from shape structs,
  same discipline as ``MXNET_TELEMETRY_HLO`` — donated buffers are
  never dereferenced; a CI/debugging mode, not a production default).
- **live accounting** (:data:`ACCOUNTANT`): a process-wide ledger of
  device-resident allocations BY SUBSYSTEM (``serve.kv_pool``,
  ``data.prefetch_ring``, ``train.params`` / ``train.opt_states`` /
  ``train.grad_accum``), exported as ``device_bytes{subsystem,device}``
  registry gauges and ``device_memory`` events, reconcilable against
  ``jax.live_arrays()`` ground truth (:func:`reconcile`).
- **budget arithmetic** (:func:`parse_bytes` / :func:`format_bytes`):
  the ``MXNET_SERVE_HBM_BUDGET`` / ``DecodeServer(hbm_budget=)``
  enforcement in ``mxnet_tpu.serve`` and the offline
  "will this config fit an N-GB chip" report
  (``tools/memory_report.py``) share these.

Reconcile caveats (docs/TELEMETRY.md "Memory" carries the full list):
the accountant stores BYTE COUNTS, not array references — a donated
buffer whose successor has the same shape (the steady-state serve pool,
the fused-step weight ring) stays correctly accounted without
re-registration, but a subsystem that frees memory without ``drop()``
leaves a stale entry (``reconcile()`` then reports ``delta < 0``).
Sharded arrays are charged per addressable shard to each shard's
device; ``jax.live_arrays()`` additionally sees everything the
accountant was never told about (jit constants, RNG keys,
unregistered weights), so ``accounted <= live`` per device is the
healthy state and the coverage ratio — not a zero delta — is the
signal.
"""
from __future__ import annotations

import os
import threading
from collections import deque

from . import events as _events
from .registry import REGISTRY

__all__ = ["mem_enabled", "parse_bytes", "format_bytes", "nbytes_of",
           "per_device_bytes", "live_device_bytes", "memory_analysis",
           "MemoryAccountant", "ACCOUNTANT", "reconcile"]


def mem_enabled():
    """``MXNET_TELEMETRY_MEM=1`` attaches ``compiled.memory_analysis()``
    fields to every compile event (read per call so tests can
    toggle it)."""
    return os.environ.get("MXNET_TELEMETRY_MEM", "0") == "1"


# --------------------------------------------------------------------- #
# byte arithmetic
# --------------------------------------------------------------------- #

_SUFFIXES = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_bytes(raw, what="byte size"):
    """``int`` bytes from an int or a ``"512M"``-style string (K/M/G/T
    suffixes, powers of 1024).  Raises ``MXNetError`` naming ``what``
    on anything else."""
    from ..base import MXNetError

    if isinstance(raw, bool):
        raise MXNetError(f"{what}: expected bytes, got {raw!r}")
    if isinstance(raw, (int, float)):
        try:
            n = int(raw)
        except (ValueError, OverflowError):   # float('inf')/nan
            raise MXNetError(
                f"{what}: expected bytes, got {raw!r}") from None
    else:
        s = str(raw).strip()
        mult = 1
        if s and s[-1].lower() in _SUFFIXES:
            mult = _SUFFIXES[s[-1].lower()]
            s = s[:-1]
        try:
            n = int(float(s) * mult)
        except (ValueError, OverflowError):   # "lots" / "1e999"
            raise MXNetError(
                f"{what}: expected bytes (int, optionally with a "
                f"K/M/G/T suffix), got {raw!r}") from None
    if n < 0:
        raise MXNetError(f"{what}: bytes must be >= 0, got {raw!r}")
    return n


def format_bytes(n):
    """Human-readable bytes (``"1.50 GiB"``) for error messages and
    report tables."""
    n = int(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


# --------------------------------------------------------------------- #
# byte walks over pytrees / live arrays
# --------------------------------------------------------------------- #

def _leaves(tree):
    """Array leaves of a pytree that may mix jax arrays, numpy arrays,
    NDArray wrappers, and plain containers (no jax import needed)."""
    if tree is None:
        return
    if isinstance(tree, (list, tuple)):
        for x in tree:
            yield from _leaves(x)
        return
    if isinstance(tree, dict):
        for x in tree.values():
            yield from _leaves(x)
        return
    inner = getattr(tree, "_data", None)   # NDArray wrapper
    if inner is not None and hasattr(inner, "nbytes"):
        yield inner
        return
    if hasattr(tree, "nbytes") and hasattr(tree, "dtype"):
        yield tree


def nbytes_of(tree):
    """Total logical bytes of every array leaf in ``tree`` (shape x
    itemsize — metadata only, never a device sync; a GLOBAL sharded
    array contributes its full logical size here, use
    :func:`per_device_bytes` for the per-device split)."""
    return sum(int(x.nbytes) for x in _leaves(tree))


def _devstr(dev):
    try:
        return f"{dev.platform}:{dev.id}"
    except Exception:
        return str(dev)


def per_device_bytes(tree):
    """``{device: bytes}`` for the array leaves of ``tree``: jax arrays
    are charged per addressable shard to each shard's device (so a
    mesh-sharded array is not over-counted), host numpy lands under
    ``"host:0"``."""
    out = {}
    for x in _leaves(tree):
        # accumulate this leaf's shard bytes LOCALLY and merge only on
        # a complete walk — a shard iteration that raises partway must
        # not leave half the leaf charged to a device AND all of it to
        # the host fallback
        leaf = {}
        shards = getattr(x, "addressable_shards", None)
        if shards is not None:
            try:
                for s in shards:
                    if s.data is not None:
                        k = _devstr(s.device)
                        leaf[k] = leaf.get(k, 0) + int(s.data.nbytes)
            except Exception:
                leaf = {}
        if not leaf:
            leaf = {"host:0": int(x.nbytes)}
        for k, b in leaf.items():
            out[k] = out.get(k, 0) + b
    return out


def live_device_bytes():
    """``{device: bytes}`` over ``jax.live_arrays()`` — the allocator's
    ground truth this process can see (per-device shard bytes, so
    sharded arrays are not charged mesh-wide)."""
    import jax

    out = {}
    try:
        live = jax.live_arrays()
    except Exception:
        return out
    for a in live:
        try:
            for s in a.addressable_shards:
                if s.data is not None:
                    k = _devstr(s.device)
                    out[k] = out.get(k, 0) + int(s.data.nbytes)
        except Exception:
            continue
    return out


# --------------------------------------------------------------------- #
# per-executable analysis
# --------------------------------------------------------------------- #

def memory_analysis(compiled):
    """XLA's buffer-assignment bytes for one compiled executable:
    ``{arg_bytes, out_bytes, temp_bytes, code_bytes, alias_bytes,
    peak_bytes}`` (``peak`` = args + outputs + temp + code - aliased;
    aliased bytes are donated inputs reused as outputs, so they are
    counted once).  ``None`` when the backend exposes no stats."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    out = {}
    for attr, key in (("argument_size_in_bytes", "arg_bytes"),
                      ("output_size_in_bytes", "out_bytes"),
                      ("temp_size_in_bytes", "temp_bytes"),
                      ("generated_code_size_in_bytes", "code_bytes"),
                      ("alias_size_in_bytes", "alias_bytes")):
        v = getattr(ma, attr, None)
        if v is not None:
            out[key] = int(v)
    if "arg_bytes" not in out and "temp_bytes" not in out:
        return None
    out["peak_bytes"] = (out.get("arg_bytes", 0) + out.get("out_bytes", 0)
                         + out.get("temp_bytes", 0)
                         + out.get("code_bytes", 0)
                         - out.get("alias_bytes", 0))
    return out


# --------------------------------------------------------------------- #
# live accounting
# --------------------------------------------------------------------- #

class MemoryAccountant:
    """Process-wide ledger of device-resident allocations by subsystem.

    ``set(subsystem, key, tree)`` (re)registers one allocation — the
    bytes are computed per device HERE and only the numbers are kept,
    never array references (registration cannot pin buffers).  Each
    mutation updates the ``device_bytes{subsystem,device}`` registry
    gauge and, when the numbers actually changed, emits one
    ``device_memory`` event (so a recorded JSONL carries the allocation
    timeline without per-batch churn — steady-state re-registration of
    an unchanged size is free)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}      # (subsystem, key) -> {device: bytes}
        # finalizer-side drop queue: __del__ paths must NEVER take
        # _lock (a GC pass can run a finalizer inside a thread that is
        # already holding it — any allocation can trigger collection),
        # so they append here (deque.append is atomic) and the entry
        # is retired on the next normal-thread mutation or query
        self._deferred = deque()

    # -- mutation -------------------------------------------------------- #
    def set(self, subsystem, key, tree=None, per_device=None):
        """Register/update allocation ``key`` of ``subsystem``: bytes
        from the array leaves of ``tree``, or an explicit
        ``per_device={device: bytes}`` mapping."""
        pd = dict(per_device) if per_device is not None \
            else per_device_bytes(tree)
        ekey = (str(subsystem), str(key))
        self._drain_deferred()
        with self._lock:
            old = self._entries.get(ekey)
            if old == pd:
                return
            self._entries[ekey] = pd
            touched = set(pd) | set(old or ())
            totals = self._totals_locked(str(subsystem), touched)
            # publish UNDER the lock: two concurrent mutations of one
            # subsystem must land their gauge totals in the order they
            # were computed, or the older total wins and the gauge
            # stays stale until the next size change (the gauge's own
            # lock nests cleanly; sinks never re-enter the accountant)
            self._publish(str(subsystem), str(key), pd, totals)

    def drop(self, subsystem, key):
        """Forget allocation ``key`` (idempotent) — call when the
        buffers are actually released, or ``reconcile()`` reports the
        stale entry as a negative delta.  NOT safe from ``__del__``
        finalizers — those use :meth:`drop_deferred`."""
        ekey = (str(subsystem), str(key))
        self._drain_deferred()
        with self._lock:
            old = self._entries.pop(ekey, None)
            if not old:
                return
            totals = self._totals_locked(str(subsystem), set(old))
            self._publish(str(subsystem), str(key),
                          {d: 0 for d in old}, totals)

    def drop_deferred(self, subsystem, key):
        """Lock-free :meth:`drop` for garbage-collection finalizers
        (``Trainer.__del__``, ``DevicePrefetchIter.__del__`` → close):
        the pair is queued atomically and retired — ledger entry
        removed, gauge zeroed, event emitted — inside the next
        ``set``/``drop``/query on a normal thread.  Queries drain
        first, so ``bytes()``/``snapshot()``/``reconcile()`` never see
        a dropped-but-queued entry; only the exported gauge may lag
        until the accountant is next touched."""
        self._deferred.append((str(subsystem), str(key)))

    def _drain_deferred(self):
        """Retire queued finalizer drops; the queue itself is touched
        only by atomic deque ops (never under ``_lock``, matching the
        lock-free enqueue), the ledger mutation takes the lock per
        item.  Callers invoke this BEFORE their own locked section —
        an entry enqueued in the gap simply waits for the next
        drain."""
        while True:
            try:
                sub, key = self._deferred.popleft()
            except IndexError:
                return
            with self._lock:
                old = self._entries.pop((sub, key), None)
                if not old:
                    continue
                totals = self._totals_locked(sub, set(old))
                self._publish(sub, key, {d: 0 for d in old}, totals)

    def _totals_locked(self, subsystem, devices):
        totals = {d: 0 for d in devices}
        for (sub, _k), pd in self._entries.items():
            if sub != subsystem:
                continue
            for d, b in pd.items():
                if d in totals:
                    totals[d] += b
        return totals

    def _publish(self, subsystem, key, pd, totals):
        for dev, total in totals.items():
            REGISTRY.gauge("device_bytes", subsystem=subsystem,
                           device=dev).set(total)
            _events.emit("device_memory", subsystem=subsystem, key=key,
                         device=dev, bytes=pd.get(dev, 0),
                         subsystem_bytes=total)

    # -- queries --------------------------------------------------------- #
    def bytes(self, subsystem=None, key=None, device=None):
        """Accounted bytes, filtered by any of subsystem/key/device."""
        total = 0
        self._drain_deferred()
        with self._lock:
            for (sub, k), pd in self._entries.items():
                if subsystem is not None and sub != str(subsystem):
                    continue
                if key is not None and k != str(key):
                    continue
                for d, b in pd.items():
                    if device is not None and d != str(device):
                        continue
                    total += b
        return total

    def snapshot(self):
        """``{subsystem: {device: bytes}}`` over every live entry."""
        out = {}
        self._drain_deferred()
        with self._lock:
            for (sub, _k), pd in self._entries.items():
                dst = out.setdefault(sub, {})
                for d, b in pd.items():
                    dst[d] = dst.get(d, 0) + b
        return out

    def reconcile(self):
        """Per-device ``{device: {accounted, live, delta, coverage}}``
        against ``jax.live_arrays()``.  ``delta = live - accounted``;
        healthy subsystems keep ``delta >= 0`` (live sees jit
        constants / unregistered weights the ledger was never told
        about), a NEGATIVE delta means a stale entry whose buffers are
        gone (see the module docstring's caveats)."""
        live = live_device_bytes()
        accounted = {}
        self._drain_deferred()
        with self._lock:
            for pd in self._entries.values():
                for d, b in pd.items():
                    accounted[d] = accounted.get(d, 0) + b
        out = {}
        for dev in set(live) | set(accounted):
            a, l = accounted.get(dev, 0), live.get(dev, 0)
            out[dev] = {"accounted": a, "live": l, "delta": l - a,
                        "coverage": (a / l) if l else None}
        return out


ACCOUNTANT = MemoryAccountant()


def reconcile():
    """Module-level shortcut for ``ACCOUNTANT.reconcile()``."""
    return ACCOUNTANT.reconcile()
