"""Process-wide metrics registry: counters, gauges, fixed-bucket
histograms.

Design constraints (ISSUE 9): always on, always cheap, thread-safe.
Recording is one small lock + integer/float arithmetic — no allocation,
no formatting, no I/O — so hot paths (one observe per decode step /
train step) pay well under a microsecond.  Exporting is pull-based:
``snapshot()`` (structured dict) and ``render_prometheus()`` (text
exposition format) walk the instruments on demand; nothing is paid at
record time for an exporter that is never called.

Callers on hot paths should hold the instrument object (returned by
:func:`counter`/:func:`gauge`/:func:`histogram`) instead of re-looking
it up per event — the lookup is a dict get, the hold is free.
"""
from __future__ import annotations

import bisect
import threading

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
           "counter", "gauge", "histogram", "snapshot",
           "render_prometheus", "reset_metrics",
           "DEFAULT_LATENCY_BUCKETS"]

# seconds-scale latency buckets: 100 us .. 60 s (plus the implicit +Inf
# overflow bucket) — wide enough for CPU-smoke decode steps and TPU
# train steps alike
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Counter:
    """Monotonic counter (a view write through ``_assign`` — the dict
    compatibility shim ``DecodeServer.counters`` uses for resets — is
    the one sanctioned non-monotonic mutation)."""

    kind = "counter"
    __slots__ = ("name", "labels", "_lock", "_n")

    def __init__(self, name, labels=()):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._n = 0

    def inc(self, n=1):
        with self._lock:
            self._n += n

    def _assign(self, n):
        """Set the count outright (counter-view resets only)."""
        with self._lock:
            self._n = n

    @property
    def value(self):
        return self._n

    def _render(self):
        return [("", self._n)]


class Gauge:
    """Last-write-wins numeric value (occupancy, ring depth, window
    position)."""

    kind = "gauge"
    __slots__ = ("name", "labels", "_lock", "_v")

    def __init__(self, name, labels=()):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v):
        with self._lock:
            self._v = v

    def add(self, n=1):
        with self._lock:
            self._v += n

    def _assign(self, v):
        with self._lock:
            self._v = v

    @property
    def value(self):
        return self._v

    def _render(self):
        return [("", self._v)]


class Histogram:
    """Fixed-bucket histogram: cumulative-on-render bucket counts plus
    sum/count/min/max.  ``observe`` is a bisect + four in-place updates
    under one lock; quantiles are estimated at read time by linear
    interpolation inside the winning bucket (clamped to the observed
    min/max so tails don't report bucket edges no sample reached)."""

    kind = "histogram"
    __slots__ = ("name", "labels", "buckets", "_lock", "_counts",
                 "_sum", "_count", "_min", "_max")

    def __init__(self, name, labels=(), buckets=None):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets if buckets is not None
                                    else DEFAULT_LATENCY_BUCKETS))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +overflow
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def _assign(self, _v):
        """Reset (the only assignment a histogram supports)."""
        with self._lock:
            for i in range(len(self._counts)):
                self._counts[i] = 0
            self._sum = 0.0
            self._count = 0
            self._min = None
            self._max = None

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """Bucket-interpolated quantile in [0, 1]; None when empty."""
        with self._lock:
            total = self._count
            if not total:
                return None
            counts = list(self._counts)
            lo_all, hi_all = self._min, self._max
        rank = q * total
        seen = 0
        for i, c in enumerate(counts):
            if seen + c >= rank and c:
                lo = self.buckets[i - 1] if i > 0 else lo_all
                hi = self.buckets[i] if i < len(self.buckets) else hi_all
                frac = (rank - seen) / c
                v = lo + (hi - lo) * frac
                return min(max(v, lo_all), hi_all)
            seen += c
        return hi_all

    def summary(self):
        """Structured snapshot: count/sum/mean/min/max/p50/p99."""
        with self._lock:
            n, s = self._count, self._sum
            lo, hi = self._min, self._max
        return {
            "count": n,
            "sum": s,
            "mean": s / n if n else None,
            "min": lo,
            "max": hi,
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
        }

    def _render(self):
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        out, cum = [], 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append((f'le="{b:g}"', cum))
        out.append(('le="+Inf"', cum + counts[-1]))
        return [("bucket", out), ("sum", s), ("count", n)]


def _label_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Registry:
    """Name+labels -> instrument table.  Get-or-create is double-checked
    under the lock; the steady-state lookup is one dict get."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    # -- get-or-create ------------------------------------------------- #
    def _get(self, cls, name, labels, **kw):
        # keyed WITHOUT kind, so re-requesting a name+labels as a
        # different instrument kind is a caller error (one exposition
        # series per name), not a silent second metric
        key = (name, labels)
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, labels, **kw)
                    self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"telemetry metric {name!r}{dict(labels)} already "
                f"registered as a {m.kind}, requested as a {cls.kind}")
        return m

    def counter(self, name, **labels) -> Counter:
        return self._get(Counter, name, _label_key(labels))

    def gauge(self, name, **labels) -> Gauge:
        return self._get(Gauge, name, _label_key(labels))

    def histogram(self, name, buckets=None, **labels) -> Histogram:
        return self._get(Histogram, name, _label_key(labels),
                         buckets=buckets)

    # -- exporters ------------------------------------------------------ #
    def _instruments(self):
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self):
        """``{name: [{labels, kind, value-or-summary}, ...]}``."""
        out = {}
        for m in self._instruments():
            row = {"labels": dict(m.labels), "kind": m.kind}
            if m.kind == "histogram":
                row.update(m.summary())
            else:
                row["value"] = m.value
            out.setdefault(m.name, []).append(row)
        return out

    def render_prometheus(self):
        """Prometheus text exposition format (one snapshot, no HTTP
        server — scrape adapters write this string wherever they like)."""
        by_name = {}
        for m in self._instruments():
            # grouped by (kind, name): a TYPE header never covers a
            # sample of another kind
            by_name.setdefault((m.kind, m.name), []).append(m)
        lines = []
        for kind, name in sorted(by_name):
            ms = by_name[(kind, name)]
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} {kind}")
            for m in ms:
                base = ",".join(f'{_prom_name(k)}="{_prom_escape(v)}"'
                                for k, v in m.labels)
                if m.kind != "histogram":
                    lines.append(
                        f"{pname}{{{base}}} {m.value:g}" if base
                        else f"{pname} {m.value:g}")
                    continue
                for part, val in m._render():
                    if part == "bucket":
                        for le, cum in val:
                            lab = f"{base},{le}" if base else le
                            lines.append(f"{pname}_bucket{{{lab}}} {cum}")
                    else:
                        lines.append(
                            f"{pname}_{part}{{{base}}} {val:g}" if base
                            else f"{pname}_{part} {val:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset_metrics(self):
        """Zero every instrument's value (instruments stay registered —
        cached references in hot paths remain valid)."""
        for m in self._instruments():
            m._assign(0)


def _prom_name(name):
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_escape(value):
    """Label VALUES per the Prometheus text exposition format: inside
    the double quotes, backslash, double-quote and line-feed must be
    escaped (``\\\\``, ``\\"``, ``\\n``) — a hostile label value (a
    file path, an error string) must not break the scrape."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


REGISTRY = Registry()


def counter(name, **labels) -> Counter:
    return REGISTRY.counter(name, **labels)


def gauge(name, **labels) -> Gauge:
    return REGISTRY.gauge(name, **labels)


def histogram(name, buckets=None, **labels) -> Histogram:
    return REGISTRY.histogram(name, buckets=buckets, **labels)


def snapshot():
    return REGISTRY.snapshot()


def render_prometheus():
    return REGISTRY.render_prometheus()


def reset_metrics():
    REGISTRY.reset_metrics()
