"""Compile/retrace watch: turn every ``jax.jit`` trace into a
queryable ``compile`` event.

``instrument_jit(fn, site, ...)`` wraps a freshly created jitted
callable.  Each call compares the executable-cache size before and
after the dispatch; growth means THIS call traced+compiled a new
signature, so one ``compile`` event is emitted carrying the site, the
producer's cache key, the wall time of the triggering call (trace +
compile + first dispatch) and the cache size after (``cache_size > 1``
is a RETRACE — the regression class tests/test_fused_step.py and
tests/test_serve.py pin, now visible in production streams).  The
registry mirrors the stream: ``compiles_total{site=}`` and
``retraces_total{site=}``.

Steady-state cost per dispatch: two ``_cache_size()`` calls (a C++
attribute read) + one ``perf_counter`` pair — noise against even a
CPU-smoke decode step.  ``MXNET_TELEMETRY=0`` returns ``fn`` unwrapped,
restoring the exact pre-telemetry dispatch path.

``MXNET_TELEMETRY_HLO=1`` additionally records the optimized-HLO
instruction count (``profiler_xla.count_hlo_ops``) on each compile
event, and ``MXNET_TELEMETRY_MEM=1`` the executable's
``memory_analysis()`` bytes (argument / output / temp / generated-code
/ peak — ``mem_*`` fields, see ``telemetry.memory``).  Either flag
lowers+compiles the signature a SECOND time through the AOT path
(shape structs only — donated buffers are never touched; both flags on
share the one recompile), so they are debugging/CI modes, not
production defaults.
"""
from __future__ import annotations

import os
import time

from . import events
from . import memory
from .registry import REGISTRY

__all__ = ["instrument_jit"]


def _hlo_wanted():
    return os.environ.get("MXNET_TELEMETRY_HLO", "0") == "1"


def instrument_jit(fn, site, key=None, fields=None):
    """Wrap jitted ``fn`` so new traces emit ``compile`` events.

    ``site`` names the producer (e.g. ``"serve.step"``); ``key`` is the
    producer's own cache key (stringified into the event); ``fields``
    are extra structured fields merged into every event from this
    wrapper (e.g. bucket sizes).  Returns ``fn`` unchanged when
    telemetry is off or ``fn`` has no executable cache to watch —
    callers never need to special-case."""
    if not events.telemetry_enabled():
        return fn
    if not hasattr(fn, "_cache_size"):
        return fn
    return _CompileWatch(fn, site, key, fields)


class _CompileWatch:
    # __weakref__ matters: jax.eval_shape (the CachedOp structure-priming
    # path) takes a weak reference to the callable it traces
    __slots__ = ("_fn", "_site", "_key", "_fields", "__weakref__")

    def __init__(self, fn, site, key, fields):
        self._fn = fn
        self._site = site
        self._key = key
        self._fields = dict(fields) if fields else {}

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            n0 = fn._cache_size()
        except Exception:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        try:
            n1 = fn._cache_size()
        except Exception:
            return out
        if n1 > n0:
            self._record(time.perf_counter() - t0, n1, args, kwargs)
        return out

    # -- event side ------------------------------------------------------ #
    def _record(self, wall, cache_size, args, kwargs):
        ev = dict(self._fields)
        ev["site"] = self._site
        if self._key is not None:
            ev["key"] = str(self._key)
        ev["wall_s"] = round(wall, 6)
        ev["cache_size"] = int(cache_size)
        retrace = cache_size > 1
        if retrace:
            ev["retrace"] = True
        want_hlo, want_mem = _hlo_wanted(), memory.mem_enabled()
        if want_hlo or want_mem:
            compiled = self._aot_compile(args, kwargs)
            if compiled is not None:
                if want_hlo:
                    n = self._hlo_ops(compiled)
                    if n is not None:
                        ev["hlo_ops"] = n
                if want_mem:
                    ma = memory.memory_analysis(compiled)
                    if ma is not None:
                        ev.update((f"mem_{k}", v) for k, v in ma.items())
        REGISTRY.counter("compiles_total", site=self._site).inc()
        if retrace:
            REGISTRY.counter("retraces_total", site=self._site).inc()
        events.emit("compile", **ev)

    def _aot_compile(self, args, kwargs):
        """Lower+compile this signature a second time from shape
        structs (already-donated input buffers are never dereferenced)
        — the one recompile both the HLO op count and the memory
        analysis read from."""
        import jax

        def struct(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        try:
            s_args, s_kwargs = jax.tree_util.tree_map(struct,
                                                      (args, kwargs))
            return self._fn.lower(*s_args, **s_kwargs).compile()
        except Exception:
            return None

    @staticmethod
    def _hlo_ops(compiled):
        """Optimized-HLO instruction count of the AOT-compiled
        signature."""
        from .. import profiler_xla

        try:
            return profiler_xla.count_hlo_ops(compiled.as_text())
        except Exception:
            return None

    # the wrapper must be a drop-in for the jitted fn: tests and callers
    # reach for ``_cache_size()`` / ``lower()`` on the returned object
    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"instrumented[{self._site}]({self._fn!r})"
