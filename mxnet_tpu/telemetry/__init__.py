"""``mx.telemetry`` — unified runtime telemetry (ISSUE 9).

One process-wide layer replaces the per-benchmark instruments the perf
claims used to rest on (module-global counter dicts, ad-hoc stopwatch
code, hand-called ``profiler_xla.hlo_op_count``):

- **metrics registry** (:mod:`.registry`): thread-safe counters /
  gauges / fixed-bucket histograms, near-zero cost to record, exported
  on demand via :func:`snapshot` or :func:`render_prometheus`.
- **event log** (:mod:`.events`): structured ``compile`` / serve-span /
  bench events in a bounded ring, fanned out to JSONL sinks
  (``MXNET_TELEMETRY_JSONL=path`` or :func:`add_jsonl_sink`);
  ``tools/telemetry_report.py`` summarizes a recorded file and
  re-checks the dispatch/retrace invariants from it alone.
- **compile watch** (:func:`instrument_jit`): every ``jax.jit`` trace
  in the hot subsystems (fused train step, CachedOp, serve pool
  programs, offline decode) emits a ``compile`` event — retrace
  regressions become a queryable stream instead of a test-only
  assertion.
- **device-timeline bridge** (:func:`annotation` / :func:`span`):
  serve/train phases appear as ``jax.profiler.TraceAnnotation`` ranges
  whenever a device trace is being captured, and cost a no-op context
  otherwise.
- **memory axis** (:mod:`.memory`, ISSUE 10): per-executable
  ``memory_analysis()`` bytes on compile events under
  ``MXNET_TELEMETRY_MEM=1``, the process-wide :data:`ACCOUNTANT`
  ledger of device-resident allocations by subsystem
  (``device_bytes{subsystem,device}`` gauges + ``device_memory``
  events, reconcilable against ``jax.live_arrays()``), and the byte
  arithmetic behind ``MXNET_SERVE_HBM_BUDGET`` / ``tools/
  memory_report.py``.

- **fault injection** (:mod:`.faults`, ISSUE 13): deterministic
  env-armed failures (``MXNET_FAULT_INJECT=site:kind:after_n``) at
  named sites in the serve scheduler, kvstore, and launch heartbeats,
  so every recovery path is exercisable in tier-1 on CPU; each firing
  emits a ``fault_injected`` event.  Free when unset.

``MXNET_TELEMETRY=0`` disables event emission and un-wraps the compile
watch (the registry itself stays live — ``DecodeServer.counters`` and
friends are views over it).  See docs/TELEMETRY.md.
"""
from __future__ import annotations

import contextlib
import time

from . import memory
from .compile import instrument_jit
from .events import (JsonlSink, add_jsonl_sink, add_sink, clear_events,
                     emit, events, remove_sink, telemetry_enabled)
from .faults import fault_point, parse_fault_spec, reset_faults
from .memory import (ACCOUNTANT, MemoryAccountant, format_bytes,
                     live_device_bytes, mem_enabled, memory_analysis,
                     nbytes_of, parse_bytes, per_device_bytes, reconcile)
from .registry import (DEFAULT_LATENCY_BUCKETS, REGISTRY, Counter, Gauge,
                       Histogram, Registry, counter, gauge, histogram,
                       render_prometheus, reset_metrics, snapshot)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram", "snapshot", "render_prometheus",
    "reset_metrics", "DEFAULT_LATENCY_BUCKETS",
    "emit", "events", "clear_events", "add_sink", "remove_sink",
    "add_jsonl_sink", "JsonlSink", "telemetry_enabled",
    "fault_point", "parse_fault_spec", "reset_faults",
    "instrument_jit", "annotation", "span",
    "memory", "ACCOUNTANT", "MemoryAccountant", "memory_analysis",
    "mem_enabled", "nbytes_of", "per_device_bytes", "live_device_bytes",
    "parse_bytes", "format_bytes", "reconcile",
]


def annotation(name):
    """A ``jax.profiler.TraceAnnotation`` context while a device trace
    is being captured (``mx.profiler.start()``), else a free no-op — so
    serve/train phases land in the device timeline exactly when someone
    is looking at one."""
    from .. import profiler

    if profiler._state["running"]:
        import jax

        return jax.profiler.TraceAnnotation(name)
    return contextlib.nullcontext()


@contextlib.contextmanager
def span(name, hist=None, **labels):
    """Time a phase into histogram ``hist`` (default
    ``f"{name}_seconds"``) and bridge it to the device timeline via
    :func:`annotation`."""
    h = REGISTRY.histogram(hist or f"{name}_seconds", **labels)
    t0 = time.perf_counter()
    with annotation(name):
        yield h
    h.observe(time.perf_counter() - t0)
