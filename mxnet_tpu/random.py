"""Random number generation.

Reference surface: ``mx.random.*`` / ``mx.nd.random_*`` ops backed by
per-device RNG resources (SURVEY.md §3.1 "Resource manager": RNG streams via
``FResourceRequest``).

TPU-native: JAX randomness is functional — a uint32 key is an explicit
input.  A process-global ``RandomState`` owns the root key and splits it per
draw (imperative path); when tracing a hybridized block, the cached
executable takes a fresh key *argument* per call and ops split from it via a
trace-key stack (so compiled dropout still differs per step — the analog of
the reference's per-invocation RNG resource)."""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError
from .ops.registry import Op, invoke

__all__ = ["seed", "uniform", "normal", "randn", "randint", "gamma",
           "exponential", "poisson", "multinomial", "shuffle", "bernoulli",
           "next_key", "current_seed", "get_state", "set_state"]

_state = threading.local()


def _root():
    if not hasattr(_state, "key"):
        # the lazy root draw is per-process on purpose (the reference's
        # per-worker RNG stream); traced code never consumes this value
        # — under a trace, next_key() splits from the trace-key STACK,
        # whose key is an executable operand, so the compiled program is
        # identical on every host:
        # tracelint: disable=TL007 -- host-side root-key bookkeeping; traced draws split the trace-key stack operand
        _state.key = jax.random.PRNGKey(onp.random.randint(0, 2**31 - 1))
        _state.seed_val = None
    return _state


def seed(seed_state, ctx="all"):
    """``mx.random.seed`` — reset the root key."""
    st = _root()
    st.key = jax.random.PRNGKey(int(seed_state))
    st.seed_val = int(seed_state)


def current_seed():
    return _root().seed_val


def get_state():
    """Snapshot the calling thread's root-key state as a host pytree
    (the ``mx.checkpoint`` RNG capture).  The trace-key stack is
    deliberately absent: it only exists while a trace is executing, and
    traced draws consume a per-call key OPERAND, not this state."""
    st = _root()
    return {"key": onp.asarray(jax.device_get(st.key)),
            "seed": st.seed_val}


def set_state(state):
    """Restore a :func:`get_state` snapshot — after this, the stream of
    :func:`next_key` splits continues exactly where the snapshot was
    taken (the bit-exact-resume contract)."""
    st = _root()
    st.key = jnp.asarray(state["key"], jnp.uint32)
    st.seed_val = state.get("seed")


# trace-key stack: pushed by CachedOp while tracing/executing jit code
def push_trace_key(key):
    st = _root()
    if not hasattr(st, "trace_stack"):
        st.trace_stack = []
    st.trace_stack.append(key)


def pop_trace_key():
    _root().trace_stack.pop()


def next_key():
    """Get a fresh PRNG key; splits trace key under jit, global key eagerly."""
    st = _root()
    stack = getattr(st, "trace_stack", None)
    if stack:
        k, sub = jax.random.split(stack[-1])
        stack[-1] = k
        return sub
    st.key, sub = jax.random.split(st.key)
    return sub


def _sample(name, fn, shape, dtype, ctx, extra_arrays=(), **params):
    from .ndarray.ndarray import NDArray
    key = next_key()

    def impl(k, *arrs):
        return fn(k, *arrs, **params).astype(jnp.dtype(dtype or "float32"))

    o = Op(name=name, fn=impl, differentiable=False)
    out = invoke(o, [NDArray(key)] + list(extra_arrays), {})
    if ctx is not None:
        out = out.as_in_context(ctx)
    return out


def _is_nd(x):
    from .ndarray.ndarray import NDArray
    return isinstance(x, NDArray)


def uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    from .ops import samplers as _s
    if _is_nd(low) or _is_nd(high):
        r = _s.sample_uniform(low, high, shape=_shape(shape),
                              dtype=dtype or "float32")
    else:
        r = _s._random_uniform(low=float(low), high=float(high),
                               shape=_shape(shape),
                               dtype=dtype or "float32")
    return _out(_ctx(r, ctx), out)


def normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    from .ops import samplers as _s
    if _is_nd(loc) or _is_nd(scale):
        r = _s.sample_normal(loc, scale, shape=_shape(shape),
                             dtype=dtype or "float32")
    else:
        r = _s._random_normal(loc=float(loc), scale=float(scale),
                              shape=_shape(shape),
                              dtype=dtype or "float32")
    return _out(_ctx(r, ctx), out)


def randn(*shape, dtype="float32", ctx=None):
    return normal(0.0, 1.0, shape or (1,), dtype=dtype, ctx=ctx)


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None):
    from .ops import samplers as _s
    r = _s._random_randint(low=int(low), high=int(high),
                           shape=_shape(shape), dtype=dtype or "int32")
    return _out(_ctx(r, ctx), out)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    from .ops import samplers as _s
    if _is_nd(alpha) or _is_nd(beta):
        r = _s.sample_gamma(alpha, beta, shape=_shape(shape),
                            dtype=dtype or "float32")
    else:
        r = _s._random_gamma(alpha=float(alpha), beta=float(beta),
                             shape=_shape(shape),
                             dtype=dtype or "float32")
    return _out(_ctx(r, ctx), out)


def exponential(scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    from .ops import samplers as _s
    if _is_nd(scale):
        # reference parameterizes by scale = 1/lam; sample_exponential
        # takes the rate lam
        r = _s.sample_exponential(1.0 / scale, shape=_shape(shape),
                                  dtype=dtype or "float32")
    else:
        r = _s._random_exponential(lam=1.0 / float(scale),
                                   shape=_shape(shape),
                                   dtype=dtype or "float32")
    return _out(_ctx(r, ctx), out)


def poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    from .ops import samplers as _s
    if _is_nd(lam):
        r = _s.sample_poisson(lam, shape=_shape(shape),
                              dtype=dtype or "float32")
    else:
        r = _s._random_poisson(lam=float(lam), shape=_shape(shape),
                               dtype=dtype or "float32")
    return _out(_ctx(r, ctx), out)


def _ctx(r, ctx):
    return r.as_in_context(ctx) if ctx is not None else r


def bernoulli(prob=0.5, shape=(1,), dtype="float32", ctx=None, out=None):
    r = _sample("_random_bernoulli",
                lambda k: jax.random.bernoulli(k, prob, tuple(_shape(shape))),
                shape, dtype, ctx)
    return _out(r, out)


def multinomial(data, shape=1, get_prob=False, dtype="int32"):
    """Sample from categorical distribution(s) given probabilities."""
    from .ndarray.ndarray import NDArray
    n = shape if isinstance(shape, int) else int(onp.prod(shape))
    key = next_key()

    def impl(k, p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        s = jax.random.categorical(k, logits, axis=-1,
                                   shape=(n,) + logits.shape[:-1])
        s = jnp.moveaxis(s, 0, -1)
        if s.shape[-1] == 1 and shape == 1:
            s = s[..., 0]
        return s.astype(jnp.dtype(dtype))

    o = Op(name="_sample_multinomial", fn=impl, differentiable=False)
    samp = invoke(o, [NDArray(key), data], {})
    if get_prob:
        from .ops import defs as _ops
        logp = _ops.log(_ops.pick(data, samp.astype("float32"), axis=-1))
        return samp, logp
    return samp


def shuffle(data, **kwargs):
    from .ndarray.ndarray import NDArray
    key = next_key()

    def impl(k, x):
        return jax.random.permutation(k, x, axis=0)

    o = Op(name="_shuffle", fn=impl, differentiable=False)
    return invoke(o, [NDArray(key), data], {})


def _shape(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


def _out(r, out):
    if out is not None:
        out._rebind(r._data)
        return out
    return r
