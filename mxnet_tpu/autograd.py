"""Tape-based autograd.

Reference surface: ``python/mxnet/autograd.py`` + ``src/imperative/``
(SURVEY.md §3.1 "Imperative runtime + autograd", anchors
``Imperative::Backward``, ``MXAutogradBackwardEx``): thread-local
recording/training flags; every invoked op appends a node to the tape (the
tape IS a graph); ``backward`` builds and runs the gradient graph.

TPU-native redesign (SURVEY.md §7 "Autograd"): we keep the explicit tape —
so ``record/pause``, ``attach_grad``/``grad_req``, ``mark_variables`` and
custom ``Function`` keep reference semantics — but each node's backward rule
is obtained by invoking the op through ``jax.vjp`` at record time.  The
returned ``vjp_fn`` closes over XLA-resident residuals, so backward is a walk
of the tape applying jax functions (which XLA fuses/dispatches async, playing
the role of the reference's engine-scheduled backward ops).
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "set_recording", "set_training", "mark_variables",
    "backward", "grad", "Function", "get_symbol", "trace_value_and_grad",
]

_STATE = threading.local()


from ._jax_compat import typeof as _typeof


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, bool(flag)
    return prev


def set_training(flag: bool) -> bool:
    st = _st()
    prev, st.training = st.training, bool(flag)
    return prev


class _ScopeCtx:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *a):
        st = _st()
        st.recording, st.training = self._old


def record(train_mode: bool = True):
    """``with autograd.record():`` — turn on recording (+training mode)."""
    return _ScopeCtx(True, train_mode)


def pause(train_mode: bool = False):
    return _ScopeCtx(False, train_mode)


def train_mode():
    return _ScopeCtx(None, True)


def predict_mode():
    return _ScopeCtx(None, False)


# ---------------------------------------------------------------------------
# Tape graph
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op invocation.  ``vjp_fn`` maps output cotangents to
    input cotangents (closing over XLA-resident residuals)."""

    __slots__ = ("name", "vjp_fn", "parents", "outputs", "out_avals",
                 "multi", "__weakref__")

    def __init__(self, name, vjp_fn, parents, out_avals, multi=None):
        self.name = name
        self.vjp_fn = vjp_fn
        # parents[i] corresponds to primal input i:
        #   ("node", TapeNode, out_idx) | ("leaf", weakref(NDArray)) | None
        self.parents = parents
        self.outputs = []  # weakrefs, set by invoke()
        self.out_avals = out_avals
        # whether vjp_fn expects a tuple cotangent (fn returned tuple/list)
        self.multi = len(out_avals) > 1 if multi is None else multi


class _FreedGraph:
    """Sentinel left on arrays whose producing node was consumed by a
    non-retaining backward: using them as *inputs* later treats them as
    constants; calling backward *on* them raises (reference: autograd
    graph-freed semantics)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst


FREED = _FreedGraph()


def _record_invoke(opref, primals, kwargs, array_args):
    """Called from ops.registry.invoke while recording: run the op through
    jax.vjp and append a tape node.  (Reference: ``Imperative::RecordOp``.)
    """
    from .ndarray.ndarray import NDArray

    # optional tensor slots may be None — vjp only over present primals
    live_idx = [i for i, p in enumerate(primals) if p is not None]
    if len(live_idx) != len(primals):
        def fn(*xs):
            full = list(primals)
            for i, x in zip(live_idx, xs):
                full[i] = x
            return opref.fn(*full, **kwargs)
        live_primals = tuple(primals[i] for i in live_idx)
    elif kwargs:
        fn = lambda *xs: opref.fn(*xs, **kwargs)
        live_primals = primals
    else:
        fn = opref.fn
        live_primals = primals
    # pause so impls composed of other wrapped ops don't double-record
    with pause(train_mode=is_training()):
        results, vjp_fn = jax.vjp(fn, *live_primals)

    parents: list = []
    for i in live_idx:
        a = array_args[i]
        if isinstance(a, NDArray):
            if a._autograd_node is FREED:
                parents.append(None)
            elif a._autograd_node is not None:
                parents.append(("node", a._autograd_node, a._autograd_idx))
            elif a._grad is not None or a._grad_req != "null":
                parents.append(("leaf", weakref.ref(a)))
            else:
                parents.append(None)
        else:
            parents.append(None)

    multi = isinstance(results, (tuple, list))
    outs = list(results) if multi else [results]
    node = TapeNode(opref.name, vjp_fn, parents,
                    [_typeof(o) for o in outs], multi=multi)
    return results, node


def _zero_cotangent(aval):
    if jnp.issubdtype(aval.dtype, jnp.floating) or jnp.issubdtype(
            aval.dtype, jnp.complexfloating):
        return jnp.zeros(aval.shape, aval.dtype)
    return onp.zeros(aval.shape, dtype=jax.dtypes.float0)


def _is_float0(x):
    return getattr(x, "dtype", None) == jax.dtypes.float0


# ---------------------------------------------------------------------------
# Backward engine
# ---------------------------------------------------------------------------

def _backward_walk(heads, head_grads, targets=None, retain_graph=False):
    """Reverse-mode walk of the tape from ``heads``.

    If ``targets`` is None: accumulate into leaf ``.grad`` per ``grad_req``
    (reference ``Imperative::Backward``).  Otherwise return cotangents for
    exactly those NDArrays (reference ``MXAutogradBackwardEx`` with
    ``var_handles`` — the ``autograd.grad`` path).
    """
    from .ndarray.ndarray import NDArray, _wrap_like

    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray) or head_grads is None:
        head_grads = [head_grads]
    else:
        head_grads = list(head_grads)
    if len(head_grads) != len(heads):
        raise MXNetError("heads and head_grads length mismatch")

    # --- seed cotangents -------------------------------------------------
    node_cots: dict[int, list] = {}   # id(node) -> per-output cotangent
    node_by_id: dict[int, TapeNode] = {}
    leaf_cots: dict[int, Any] = {}    # id(ndarray) -> cotangent
    leaf_by_id: dict[int, NDArray] = {}

    def add_node_cot(node, idx, val):
        nid = id(node)
        node_by_id[nid] = node
        lst = node_cots.setdefault(nid, [None] * len(node.out_avals))
        lst[idx] = val if lst[idx] is None else lst[idx] + val

    def add_leaf_cot(arr, val):
        if _is_float0(val):
            return
        aid = id(arr)
        leaf_by_id[aid] = arr
        leaf_cots[aid] = val if aid not in leaf_cots else leaf_cots[aid] + val

    target_ids = None
    if targets is not None:
        target_ids = {id(t) for t in targets}

    for h, hg in zip(heads, head_grads):
        g = hg._data if isinstance(hg, NDArray) else hg
        if g is None:
            aval = _typeof(h._data)
            g = jnp.ones(aval.shape, aval.dtype) if jnp.issubdtype(
                aval.dtype, jnp.floating) else _zero_cotangent(aval)
        if h._autograd_node is FREED:
            raise MXNetError(
                "graph already freed: call backward(retain_graph=True) to "
                "backprop through the same graph twice")
        if h._autograd_node is not None:
            add_node_cot(h._autograd_node, h._autograd_idx, g)
        else:
            add_leaf_cot(h, g)

    # --- topo order: consumers before producers --------------------------
    order: list[TapeNode] = []
    seen: set[int] = set()
    root_nodes = [h._autograd_node for h in heads if h._autograd_node]
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, done = stack.pop()
        nid = id(node)
        if done:
            order.append(node)
            continue
        if nid in seen:
            continue
        seen.add(nid)
        stack.append((node, True))
        for p in node.parents:
            if p is not None and p[0] == "node" and id(p[1]) not in seen:
                stack.append((p[1], False))
    order.reverse()  # consumers first

    # cotangents captured for explicit targets that are intermediates
    target_node_cots: dict[int, Any] = {}

    # --- walk ------------------------------------------------------------
    for node in order:
        nid = id(node)
        cots = node_cots.get(nid)
        if cots is None:
            continue
        filled = [c if c is not None else _zero_cotangent(a)
                  for c, a in zip(cots, node.out_avals)]
        if node.vjp_fn is None:
            raise MXNetError(
                "graph already freed: call backward(retain_graph=True) to "
                "backprop through the same graph twice")
        arg = tuple(filled) if node.multi else filled[0]
        in_cots = node.vjp_fn(arg)
        if not retain_graph:
            node.vjp_fn = None  # free residuals
            for outref in node.outputs:
                o = outref() if outref else None
                if o is not None and o._autograd_node is node:
                    o._autograd_node = FREED
        # record cotangents for explicit intermediate targets
        if target_ids:
            for outref in node.outputs:
                o = outref() if outref else None
                if o is not None and id(o) in target_ids:
                    c = filled[o._autograd_idx]
                    tid = id(o)
                    target_node_cots[tid] = (
                        c if tid not in target_node_cots
                        else target_node_cots[tid] + c)
        for p, c in zip(node.parents, in_cots):
            if p is None or _is_float0(c):
                continue
            if p[0] == "node":
                add_node_cot(p[1], p[2], c)
            else:
                arr = p[1]()
                if arr is not None:
                    add_leaf_cot(arr, c)

    # --- commit ----------------------------------------------------------
    if targets is not None:
        out = []
        for t in targets:
            tid = id(t)
            c = target_node_cots.get(tid, leaf_cots.get(tid))
            if c is None:
                c = jnp.zeros(t.shape, t.dtype)
            out.append(_wrap_like(c, t))
        return out

    for aid, c in leaf_cots.items():
        arr = leaf_by_id[aid]
        if arr._grad_req == "null" or arr._grad is None:
            continue
        if arr._grad_req == "add":
            arr._grad._rebind(arr._grad._data + c)
        else:  # write
            arr._grad._rebind(jnp.asarray(c, arr._grad._data.dtype)
                              if c.dtype != arr._grad._data.dtype else c)
    return None


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """``mx.autograd.backward`` — grads land in ``x.grad``."""
    with pause(train_mode=train_mode):
        _backward_walk(heads, head_grads, None, retain_graph)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """``mx.autograd.grad`` — return grads w.r.t. ``variables`` without
    touching ``.grad``.  ``create_graph`` (higher-order) is not yet
    supported and raises (documented descope for now)."""
    from .ndarray.ndarray import NDArray

    if create_graph:
        raise MXNetError("create_graph=True (higher-order grad) not yet "
                         "supported; use jax.grad via block.apply for "
                         "higher-order derivatives")
    single = isinstance(variables, NDArray)
    targets = [variables] if single else list(variables)
    if retain_graph is None:
        retain_graph = create_graph
    with pause(train_mode=train_mode):
        outs = _backward_walk(heads, head_grads, targets, retain_graph)
    return outs[0] if single else outs


def trace_value_and_grad(fn, params, frozen_params=(), train_mode=True):
    """Grad-and-value capture for the fused train step — the tape is never
    materialized.

    Where ``record()``/``backward()`` append one TapeNode per op and walk
    it afterwards, this functionalizes the whole ``fn`` call (forward +
    loss) and differentiates it with ``jax.value_and_grad``, so a single
    XLA program carries forward AND backward (the reference's
    whole-step-behind-CachedOp amalgamation, SURVEY.md §4.2).  Returns a
    PURE function, intended to be traced inside ``jax.jit``::

        pure(key, train_vals, frozen_vals, *args)
            -> (outs, grads, new_frozen_vals)

    - ``fn`` is NDArray-level user code (e.g. ``lambda x, y:
      loss(net(x), y)``); it may return a single loss or a tuple whose
      FIRST element is the loss (extra outputs — predictions — ride along
      undifferentiated).
    - ``params``/``frozen_params`` are the Parameters whose values ride
      in as ``train_vals``/``frozen_vals`` operands (CachedOp's
      weights-as-arguments discipline, via ``params_swapped``).
    - The backward is seeded with the gradient of ``sum(loss)`` — the
      identical seeding to ``loss.backward()`` on the tape path.
    - ``new_frozen_vals`` are the frozen params' values with staged aux
      updates (BN moving stats) applied, aligned with ``frozen_params``.
    - ``pure.out_struct['is_seq']`` records (at first trace) whether
      ``fn`` returned a sequence.
    """
    from .gluon.block import trace_scope
    from .gluon.parameter import params_swapped
    from .ndarray.ndarray import NDArray

    params = list(params)
    frozen = list(frozen_params)
    all_params = params + frozen
    struct: dict = {}

    def run(key, train_vals, frozen_vals, args):
        all_vals = list(train_vals) + list(frozen_vals)
        with trace_scope(key, train_mode) as aux:
            with params_swapped(all_params, all_vals):
                nd_args = [a if isinstance(a, NDArray) else NDArray(a)
                           for a in args]
                out = fn(*nd_args)
        is_seq = isinstance(out, (tuple, list))
        struct["is_seq"] = is_seq
        outs = [o._data if isinstance(o, NDArray) else o
                for o in (out if is_seq else [out])]
        aux_by_id = {id(p): jax.lax.stop_gradient(v)
                     for (p, v) in aux.values()}
        new_frozen = [aux_by_id.get(id(p), v)
                      for p, v in zip(frozen, frozen_vals)]
        return outs, new_frozen

    def pure(key, train_vals, frozen_vals, *args):
        def loss_of(tv):
            outs, new_frozen = run(key, tv, frozen_vals, args)
            return jnp.sum(outs[0]), (outs, new_frozen)

        (_, (outs, new_frozen)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(tuple(train_vals))
        return tuple(outs), grads, new_frozen

    pure.out_struct = struct
    return pure


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference
    ``MXAutogradMarkVariables``)."""
    from .ndarray.ndarray import NDArray

    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, r in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = r


def get_symbol(x):
    """Reference returns the recorded Symbol; here the tape has no separate
    symbolic IR — use ``HybridBlock.export`` for graph capture."""
    raise MXNetError("get_symbol: tape-to-symbol export not supported; "
                     "hybridize + export() instead")


# ---------------------------------------------------------------------------
# Custom Function (reference: mx.autograd.Function -> CustomOp thread pool;
# here backward is just a python callback wired as the node's vjp)
# ---------------------------------------------------------------------------

class Function:
    """User-defined differentiable function with explicit backward."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap_like

        with pause(train_mode=is_training()):
            outputs = self.forward(*inputs)
        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        if not is_recording():
            return outputs

        func = self

        def vjp_fn(cots):
            cots = cots if isinstance(cots, tuple) else (cots,)
            nd_cots = [_wrap_like(c, None) for c in cots]
            with pause():
                grads = func.backward(*nd_cots)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            return tuple(g._data if isinstance(g, NDArray) else g
                         for g in grads)

        parents = []
        for a in inputs:
            if isinstance(a, NDArray):
                if a._autograd_node is FREED:
                    parents.append(None)
                elif a._autograd_node is not None:
                    parents.append(("node", a._autograd_node, a._autograd_idx))
                else:
                    parents.append(("leaf", weakref.ref(a)))
            else:
                parents.append(None)
        node = TapeNode(type(self).__name__, vjp_fn, parents,
                        [_typeof(o._data) for o in outs], multi=multi)
        for i, o in enumerate(outs):
            o._autograd_node = node
            o._autograd_idx = i
        node.outputs = [o._weak() for o in outs]
        return outputs
