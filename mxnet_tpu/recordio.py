"""RecordIO — the reference's binary record container format.

Reference surface: ``python/mxnet/recordio.py`` (``MXRecordIO``,
``MXIndexedRecordIO``, ``IRHeader``, ``pack/unpack/pack_img/unpack_img``)
backed by ``dmlc::RecordIOWriter/Reader`` in ``3rdparty/dmlc-core``
(SURVEY.md §3.1 "dmlc-core" row, anchor ``dmlc::RecordIOWriter``; §3.2
"io / recordio / image" row).

File layout (dmlc recordio, public format):

  record := uint32 kMagic(0xced7230a)
          | uint32 lrec          # upper 3 bits = cflag, lower 29 = length
          | data[length]
          | pad to 4-byte boundary

cflag encodes multi-part records for payloads that themselves contain the
magic; this writer always emits whole records (cflag=0) and the reader
reassembles split ones, matching dmlc semantics.

When the native C++ pipeline library is built (``mxnet_tpu/_native``), reads
go through it for throughput; this pure-Python path is the always-available
fallback and the reference for correctness tests.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as onp

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_KMAGIC = 0xCED7230A
_CFLAG_BITS = 29
_LEN_MASK = (1 << _CFLAG_BITS) - 1


def _encode_lrec(cflag: int, length: int) -> int:
    return (cflag << _CFLAG_BITS) | length


def _decode_lrec(lrec: int):
    return lrec >> _CFLAG_BITS, lrec & _LEN_MASK


class MXRecordIO:
    """Sequential reader/writer for ``.rec`` files.

    Matches the reference API: ``open/close/reset/write/read/tell/seek``
    (seek only on readers via byte offsets, as used by the indexed variant).
    """

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"Invalid flag {self.flag!r}")
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self.record.tell()

    def seek(self, pos: int):
        if self.writable:
            raise MXNetError("seek only supported on readers")
        self.record.seek(pos)

    def write(self, buf: bytes):
        if not self.writable:
            raise MXNetError("not opened for writing")
        if not isinstance(buf, (bytes, bytearray, memoryview)):
            raise MXNetError("write expects bytes")
        self.record.write(struct.pack("<II", _KMAGIC,
                                      _encode_lrec(0, len(buf))))
        self.record.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        """Read the next record; ``None`` at EOF."""
        if self.writable:
            raise MXNetError("not opened for reading")
        parts = []
        while True:
            head = self.record.read(8)
            if len(head) < 8:
                if parts:
                    raise MXNetError("truncated multi-part record")
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _KMAGIC:
                raise MXNetError(f"bad record magic {magic:#x}")
            cflag, length = _decode_lrec(lrec)
            data = self.record.read(length)
            if len(data) < length:
                raise MXNetError("truncated record payload")
            self.record.read((-length) % 4)
            parts.append(data)
            # dmlc cflag: 0 whole, 1 first-of-many, 2 middle, 3 last
            if cflag == 0:
                return data
            if cflag == 3:
                return b"".join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer using a sidecar ``.idx`` text file
    (``key\\tbyte_offset`` per line, the reference's im2rec layout)."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type: type = int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    line = line.strip().split("\t")
                    if len(line) < 2:
                        continue
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf: bytes):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


# --------------------------------------------------------------------- #
# IRHeader packing (image records)
# --------------------------------------------------------------------- #
IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a string payload with an ``IRHeader``.  If ``header.label`` is an
    array, ``flag`` is set to its length and the float32 label vector is
    written between header and payload (reference ``recordio.pack``)."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (list, tuple)) or hasattr(label, "ndim"):
        label = onp.asarray(label, dtype=onp.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, int(header.flag), float(header.label),
                       int(header.id), int(header.id2)) + s


def unpack(s: bytes):
    """Inverse of :func:`pack` → ``(IRHeader, payload_bytes)``."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:4 * header.flag], dtype=onp.float32)
        header = header._replace(label=label)
        s = s[4 * header.flag:]
    return header, s


def pack_img(header: IRHeader, img, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode an HWC uint8 image array and pack it (reference
    ``recordio.pack_img``; OpenCV there, PIL here)."""
    from .image import imencode
    return pack(header, imencode(img, quality=quality, img_fmt=img_fmt))


def unpack_img(s: bytes, iscolor: int = -1):
    """→ ``(IRHeader, HWC uint8 numpy image)``."""
    from .image import imdecode_np
    header, img_bytes = unpack(s)
    return header, imdecode_np(img_bytes, iscolor=iscolor)
