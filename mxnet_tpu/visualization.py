"""``mx.viz`` — network visualization (reference
``python/mxnet/visualization.py``): ``print_summary`` (layer table over a
Symbol) and ``plot_network`` (graphviz digraph, gated on the graphviz
package)."""
from __future__ import annotations

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a per-node summary table of a Symbol graph (reference
    ``mx.viz.print_summary``); with ``shape`` (dict of input shapes) also
    infers and prints output shapes and parameter counts."""
    from .symbol.symbol import Symbol, _topo, infer_args
    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary expects a Symbol")
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    positions = [int(line_length * p) for p in positions]
    shapes = None
    arg_shapes = {}
    if shape is not None:
        arg_shapes = infer_args(symbol, **shape)

    def row(fields):
        line = ""
        for field, pos in zip(fields, positions):
            line = (line + str(field))[:pos].ljust(pos)
        print(line)

    print("=" * line_length)
    row(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print("=" * line_length)
    total = 0
    nodes = _topo(symbol._heads)
    for node in nodes:
        if node.op is None:
            shp = arg_shapes.get(node.name, "")
            row([f"{node.name} (null)", shp, 0, ""])
            continue
        n_params = 0
        prevs = []
        for inp, _ in node.inputs:
            prevs.append(inp.name)
            if inp.op is None and inp.name in arg_shapes \
                    and not _is_data_name(inp.name):
                n = 1
                for d in arg_shapes[inp.name]:
                    n *= d
                n_params += n
        total += n_params
        out_shape = ""
        row([f"{node.name} ({node.op})", out_shape, n_params,
             ",".join(prevs[:3])])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


def _is_data_name(name):
    return name in ("data", "softmax_label", "label") or \
        name.startswith("data")


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the Symbol graph (reference ``plot_network``).
    Requires the ``graphviz`` package; raises a clear error otherwise."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the graphviz package (not installed in "
            "this environment); use print_summary or symbol.tojson") from e
    from .symbol.symbol import Symbol, _topo
    if not isinstance(symbol, Symbol):
        raise MXNetError("plot_network expects a Symbol")
    dot = Digraph(name=title, format=save_format)
    nodes = _topo(symbol._heads)
    for node in nodes:
        if node.op is None:
            if hide_weights and not _is_data_name(node.name):
                continue
            dot.node(node.name, node.name, shape="oval",
                     **(node_attrs or {}))
        else:
            dot.node(node.name, f"{node.name}\n{node.op}", shape="box",
                     **(node_attrs or {}))
    present = {n.name for n in nodes
               if n.op is not None or not hide_weights
               or _is_data_name(n.name)}
    for node in nodes:
        if node.op is None:
            continue
        for inp, _ in node.inputs:
            if inp.name in present:
                dot.edge(inp.name, node.name)
    return dot
