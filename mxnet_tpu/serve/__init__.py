"""Continuous-batching decode serving (``mx.serve``).

The "millions of users" workload on top of the KV-cache decode stack:
a request queue + scheduler where ragged requests join the running
compiled decode step at step boundaries, sharing ONE resident slot-pool
K/V cache (``docs/SERVING.md``).

    server = mx.serve.DecodeServer(net, max_total_len=256)
    stream = server.submit(prompt_ids, max_new_tokens=64)
    for tok in stream:          # tokens as they decode
        ...
    server.close()
"""
from .draft import Drafter, NGramDrafter
from .server import (DecodeServer, TokenStream, serve_counters,
                     reset_serve_counters)
from .engine import PoolPrograms

__all__ = ["DecodeServer", "TokenStream", "PoolPrograms",
           "Drafter", "NGramDrafter",
           "serve_counters", "reset_serve_counters"]
