"""Host-side draft proposers for speculative decoding
(``mxnet_tpu.serve.DecodeServer``).

A drafter is CHEAP HOST CODE on the scheduler thread: between decode
dispatches it proposes up to ``k`` continuation tokens per slot from
that slot's prompt + generated history, and ONE bucketed ``(S, k)``
verify executable (``serve.engine.PoolPrograms.verify_fn``) scores
every proposal in a single dispatch — accepted drafts cost a fraction
of a dispatch each instead of one full step.  A drafter never touches
the device and never sees model weights, so a bad proposal costs
nothing but the verify column it rode in; a GOOD proposal must match
the model's own greedy emission, which is why self-speculation (the
sequence predicting its own continuation) is the zero-cost default.

The interface is deliberately one method, so a small zoo model (or any
future learned drafter) slots in by implementing ``propose``:

```python
class MyDrafter(Drafter):
    def propose(self, history, k):      # history: 1-D int numpy
        return my_tokens[:k]            # <= k ints, [] to skip
```

``NGramDrafter`` is the shipped default: longest-suffix n-gram
self-speculation.  It finds the most recent earlier occurrence of the
longest suffix (down to ``min_match`` tokens) of the slot's history
and proposes the tokens that followed it — repetitive continuations
(code, lists, template prose, greedy loops) verify at high acceptance,
and histories with no repeated suffix propose nothing (the slot takes
a plain step, costing exactly what it costs today).
"""
from __future__ import annotations

import numpy as onp

__all__ = ["Drafter", "NGramDrafter"]


class Drafter:
    """Pluggable draft-proposal interface (host-side, per slot)."""

    def propose(self, history, k):
        """Up to ``k`` proposed continuation tokens for one slot.

        ``history`` is the slot's full token context — prompt +
        every generated token routed to its stream so far — as a 1-D
        int numpy array.  Return a sequence of at most ``k`` ints (a
        list or 1-D array); return an empty sequence to skip this slot
        (it runs a plain step).  Called on the scheduler thread between
        dispatches: must be cheap and must not block."""
        raise NotImplementedError

    def observe(self, history, accepted, rejected):
        """Optional acceptance feedback after a verify drain (default:
        ignored).  Adaptive drafters can tune per-slot depth here."""


class NGramDrafter(Drafter):
    """Longest-suffix n-gram self-speculation.

    Matches the longest suffix of ``history`` (length ``max_match``
    down to ``min_match``) against its most recent EARLIER occurrence
    and proposes the ``k`` tokens that followed that occurrence.  Pure
    numpy over a bounded window (``window`` trailing tokens), so a
    proposal costs microseconds against the milliseconds a decode
    dispatch costs."""

    def __init__(self, min_match=1, max_match=4, window=512):
        if min_match < 1 or max_match < min_match:
            raise ValueError(f"need 1 <= min_match <= max_match, got "
                             f"{min_match}..{max_match}")
        self.min_match = int(min_match)
        self.max_match = int(max_match)
        self.window = int(window)

    def propose(self, history, k):
        hist = onp.asarray(history, dtype=onp.int64).ravel()
        if k < 1 or hist.size < self.min_match + 1:
            return []
        base = max(0, hist.size - self.window)
        h = hist[base:]
        n = h.size
        for m in range(min(self.max_match, n - 1), self.min_match - 1,
                       -1):
            suffix = h[n - m:]
            # candidate start positions of earlier suffix occurrences
            # (excluding the suffix itself); most recent match wins —
            # locality: the continuation that followed last time is
            # the likeliest to follow again
            starts = n - m - 1
            if starts < 1:
                continue
            windows = onp.lib.stride_tricks.sliding_window_view(
                h[:n - 1], m)[:starts]
            hits = onp.nonzero((windows == suffix).all(axis=1))[0]
            if hits.size:
                j = int(hits[-1]) + m      # first token AFTER the match
                return [int(t) for t in h[j:j + k]]
        return []
