"""Declarative operand schema for the serve pool executables.

This module is the SINGLE SOURCE OF TRUTH for the positional contracts
the paged slot-pool programs (``serve.engine.PoolPrograms``) live by:

* ``EXECUTABLES`` — each compiled program's operand list (name + order),
  which operands are donated to XLA, and the layout of its packed
  ``meta`` row.  ``jax.jit(..., donate_argnums=...)`` trusts these
  positions blindly: a new operand inserted without shifting the
  donation indices silently donates the WRONG buffer (the PR-18
  recycled-page bug rode exactly that hand-shifted pair), so the
  engine derives its ``donate_argnums`` from here instead of literals
  (:func:`jit_donate` also cross-checks the wrapped function's actual
  signature at program-build time).
* ``SLOT_STATE`` — the per-slot scalar state columns riding alongside
  the K/V page pools, in tuple order, with dtype and per-slot element
  count.  ``pool_state_bytes``/``admit_scratch_bytes`` price slots at
  :func:`slot_state_bytes` and ``tools/telemetry_report.py
  --check-serve`` re-derives the same figure from this file (loaded
  standalone, by path), so the byte ledger can never drift from the
  layout.

Both declarations are PURE LITERALS on purpose: ``tools/tracelint``'s
executable-contract rules (TL016–TL018) read them straight out of the
AST — no import, no execution — and hold every ``jax.jit`` donation
tuple, meta subscript and dispatch call-site in the lint target to the
same contract the runtime enforces.

This module imports nothing from the package (and no third-party
modules) so standalone tools can load it by file path.
"""
from __future__ import annotations

__all__ = ["EXECUTABLES", "SLOT_STATE", "KV_PAGE_INT8",
           "executable_names", "operands",
           "arity", "donate_argnums", "donated_operands", "jit_donate",
           "state_operands", "state_arity", "slot_state_fields",
           "slot_state_bytes", "kv_page_int8_bytes", "meta_fields",
           "meta_width", "meta_col", "meta_cols", "meta_row"]

# -- the per-slot scalar state block ------------------------------------ #
# (name, dtype, elements-per-slot) in TUPLE ORDER: the state operand
# tuple every executable threads through is ``(kp, vp, *columns)``.
# ``keys`` is the 2-word per-slot PRNG key; ``dl`` the wall-clock
# retirement deadline (server-epoch seconds, +inf = none); ``spec`` the
# per-slot speculation-depth cap.
SLOT_STATE = (
    ("pos",    "int32",   1),   # next write index
    ("tok",    "int32",   1),   # last sampled token
    ("active", "bool",    1),   # slot live?
    ("stop",   "int32",   1),   # retire position
    ("keys",   "uint32",  2),   # per-slot PRNG key
    ("dl",     "float32", 1),   # per-slot deadline
    ("spec",   "int32",   1),   # speculation-depth cap
)

# -- the compiled programs ---------------------------------------------- #
# ``operands``: the wrapped function's positional parameters, in order.
# ``donated``: operand NAMES donated to XLA (the engine turns these
# into positions — always the page-pool pair today, but the indices
# differ per program because each has its own operand prefix).
# ``meta``: the packed int32 meta row's field order (() = no meta).
# ``getter``: the ``PoolPrograms`` method handing out the jitted fn —
# the linter resolves server-side dispatch call-sites through it.
# ``module``: dotted module (suffix-matched) defining the program.
EXECUTABLES = {
    "step": {
        "module": "mxnet_tpu.serve.engine",
        "getter": "step_fn",
        "telemetry": "serve.step",
        "operands": ("param_vals", "q8", "sw", "now", "pt",
                     "kp", "vp", "pos", "tok", "active", "stop",
                     "keys", "dl", "spec"),
        "donated": ("kp", "vp"),
        "meta": (),
    },
    "admit": {
        "module": "mxnet_tpu.serve.engine",
        "getter": "admit_fn",
        "telemetry": "serve.admit",
        "operands": ("param_vals", "prompts", "meta", "dls", "pages",
                     "zpages", "kp", "vp", "pos", "tok", "active",
                     "stop", "keys", "dl", "spec"),
        "donated": ("kp", "vp"),
        "meta": ("valid", "true_len", "slot", "stop_pos", "seed",
                 "spec_depth"),
    },
    "hit": {
        "module": "mxnet_tpu.serve.engine",
        "getter": "admit_hit_fn",
        "telemetry": "serve.admit_hit",
        "operands": ("meta", "dls", "src", "dst", "zpages",
                     "kp", "vp", "pos", "tok", "active", "stop",
                     "keys", "dl", "spec"),
        "donated": ("kp", "vp"),
        "meta": ("valid", "true_len", "slot", "stop_pos", "seed",
                 "last_tok", "spec_depth"),
    },
    "chunk": {
        "module": "mxnet_tpu.serve.engine",
        "getter": "chunk_fn",
        "telemetry": "serve.chunk",
        "operands": ("param_vals", "q8", "sw", "toks", "meta", "dls",
                     "ptrow", "zrow", "kp", "vp", "pos", "tok",
                     "active", "stop", "keys", "dl", "spec"),
        "donated": ("kp", "vp"),
        "meta": ("final", "slot", "true_len", "stop_pos", "seed",
                 "nlast", "off", "spec_depth"),
    },
    "verify": {
        "module": "mxnet_tpu.serve.engine",
        "getter": "verify_fn",
        "telemetry": "serve.verify",
        "operands": ("param_vals", "q8", "sw", "now", "pt", "drafts",
                     "nd", "kp", "vp", "pos", "tok", "active", "stop",
                     "keys", "dl", "spec"),
        "donated": ("kp", "vp"),
        "meta": (),
    },
}

# the int8-quantized K/V page representation (``kv_dtype="int8"``):
# each page stores codes at 1 byte/element plus ONE scale per
# (layer, KV head) for each of K and V.  ``models.decoding._kv_requant``
# produces exactly this pair (its ``_KV_CODE_DTYPE``/``_KV_SCALE_DTYPE``
# constants are test-pinned to these names) and ``PoolPrograms.
# page_bytes`` prices pages from it.
KV_PAGE_INT8 = {"codes": "int8", "scales": "float32"}

_ITEMSIZE = {"bool": 1, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
             "int32": 4, "uint32": 4, "float32": 4, "int64": 8,
             "uint64": 8, "float64": 8}


def executable_names():
    """Declared program names, in declaration order."""
    return tuple(EXECUTABLES)


def _entry(name):
    try:
        return EXECUTABLES[name]
    except KeyError:
        raise ValueError(
            f"no serve executable named {name!r} in the operand schema "
            f"(declared: {', '.join(EXECUTABLES)})") from None


def operands(name):
    """The positional operand names of executable ``name``, in order."""
    return _entry(name)["operands"]


def arity(name):
    """Positional operand count of executable ``name``."""
    return len(operands(name))


def donated_operands(name):
    """The operand NAMES executable ``name`` donates."""
    return _entry(name)["donated"]


def donate_argnums(name):
    """The donation POSITIONS of executable ``name`` — derived from the
    declared operand order, never hand-counted."""
    ops = operands(name)
    donated = donated_operands(name)
    missing = [d for d in donated if d not in ops]
    if missing:
        raise ValueError(
            f"executable {name!r} declares donated operand(s) "
            f"{missing} absent from its operand list")
    return tuple(i for i, op in enumerate(ops) if op in donated)


def jit_donate(name, fn):
    """Validate ``fn``'s positional signature against the declaration
    and return the registry-derived ``donate_argnums`` for ``name``.

    This is the program-build-time enforcement point: the engine passes
    every pool executable through here, so an operand added to the
    function without updating the schema (or vice versa) raises before
    anything compiles — the same drift tracelint TL016/TL018 catches
    statically.
    """
    import inspect

    declared = operands(name)
    kinds = (inspect.Parameter.POSITIONAL_ONLY,
             inspect.Parameter.POSITIONAL_OR_KEYWORD)
    actual = tuple(p.name for p in
                   inspect.signature(fn).parameters.values()
                   if p.kind in kinds)
    if actual != declared:
        raise ValueError(
            f"executable {name!r} signature drifted from the operand "
            f"schema:\n  declared: {declared}\n  actual:   {actual}\n"
            "update mxnet_tpu/serve/schema.py and the function "
            "together — donation indices and call sites derive from "
            "the declaration")
    return donate_argnums(name)


# -- slot-state layout --------------------------------------------------- #

def slot_state_fields():
    """The per-slot scalar columns ``(name, dtype, elements)``."""
    return SLOT_STATE


def state_operands():
    """The full state operand block every executable's tail threads:
    the K/V page pools followed by the scalar columns, in tuple
    order."""
    return ("kp", "vp") + tuple(n for n, _, _ in SLOT_STATE)


def state_arity():
    """Element count of the pool state tuple."""
    return 2 + len(SLOT_STATE)


def slot_state_bytes():
    """Device bytes of ONE slot's scalar state — the pricing constant
    ``pool_state_bytes``/``admit_scratch_bytes`` scale and
    ``telemetry_report --check-serve`` re-derives."""
    return sum(_ITEMSIZE[dtype] * n for _, dtype, n in SLOT_STATE)


def kv_page_int8_bytes(nl, kv, page, d):
    """Device bytes of ONE int8-quantized page across all layers, K
    and V pools together, priced from the declared ``KV_PAGE_INT8``
    layout: ``page * d`` codes plus one scale per (layer, KV head)."""
    return 2 * nl * kv * (page * d * _ITEMSIZE[KV_PAGE_INT8["codes"]]
                          + _ITEMSIZE[KV_PAGE_INT8["scales"]])


# -- meta rows ----------------------------------------------------------- #

def meta_fields(name):
    """The packed int32 meta-row field order of executable ``name``."""
    return _entry(name)["meta"]


def meta_width(name):
    """Column count of executable ``name``'s meta row."""
    return len(meta_fields(name))


def meta_col(name, field):
    """Column index of ``field`` in executable ``name``'s meta row."""
    fields = meta_fields(name)
    try:
        return fields.index(field)
    except ValueError:
        raise ValueError(
            f"executable {name!r} has no meta field {field!r} "
            f"(declared: {fields})") from None


def meta_cols(name):
    """``{field: column}`` for executable ``name``'s meta row."""
    return {f: i for i, f in enumerate(meta_fields(name))}


def meta_row(name, **fields):
    """Assemble one meta row as a tuple in DECLARED column order.

    Every declared field must be supplied by keyword (and nothing
    else), so a new column added to the declaration immediately breaks
    every builder that has not been taught about it — the host-side
    mirror of :func:`jit_donate`.
    """
    layout = meta_fields(name)
    extra = sorted(set(fields) - set(layout))
    missing = [f for f in layout if f not in fields]
    if extra or missing:
        raise ValueError(
            f"meta_row({name!r}) fields disagree with the schema: "
            f"missing {missing}, unexpected {extra} "
            f"(declared order: {layout})")
    return tuple(fields[f] for f in layout)
