"""Paged slot-pool decode programs — the device side of the continuous-
batching server (``mxnet_tpu.serve.server``).

The resident K/V store is a PAGE POOL: one ``(NL, NPAGES, KV, PAGE, D)``
array pair shared by all in-flight sequences, addressed through per-slot
page tables (``(S, MAXP)`` int32 rows, host-owned, passed as TRACED
OPERANDS on every dispatch — allocation churn changes table VALUES,
never shapes, so the compiled programs survive any admit/retire/append
pattern with zero retraces).  A sequence holds only the pages its tokens
occupy, so a long-context ragged mix packs ~T/len(x) more sequences into
the same HBM than the dense per-slot ``T``-column layout this replaces.
Per-slot position / last-token / active / stop / sampling-key /
wall-clock-deadline state rides alongside, so admission and retirement —
including deadline expiry against the step's ``now`` operand — stay
device-side masked updates: no recompile, no host sync in the step.

The one-past-the-end page id ``NPAGES`` is the table SENTINEL: gathers
through it fill zeros and scatters through it DROP.  Retired/idle slots
carry all-sentinel rows, which is what makes masked zombie lanes safe —
a freed (or reused) page can never be corrupted by a slot that no longer
owns it, and the overwrite-before-unmask invariant (a decode step at
position ``q`` writes its own column before attending) covers everything
a live slot can read.

Compiled units per pool size ``S``:

- **step** — ``_DecodeEngine.pool_token_paged`` (the stacked-layer scan
  gathering/scattering through the page tables) + per-slot sampling +
  retirement flags, jitted with the page pools donated: ONE executable
  dispatch per decode step (``tests/test_serve.py`` pins the count).
- **admit(A_bucket, P_bucket)** — ONE causal prefill over an ``(A, P)``
  block of right-padded prompts; the K/V stream lands in the admitted
  slots' RESERVED PAGES via one masked page scatter (rows/pages beyond
  the wave aim at the sentinel and drop), and the ``A`` first tokens +
  done flags come back in one readback.
- **admit_hit(A_bucket)** — prefix-cache hit admission: NO model
  forward at all.  The slot enters at ``pos = L - 1`` mapping the shared
  prefix pages read-only (plus at most one copy-on-write page copy per
  row when the prompt ends exactly on a shared page boundary), and the
  next regular step recomputes the last prompt token — sampling with
  ``fold_in(key, L-1)``, the exact key the batched admit uses, so hit
  and miss streams are token-identical while a hit's TTFT is one step.
- **chunk(C_bucket)** — chunked prefill: one ``C``-token slice of a
  single long prompt runs against the slot's page-table row
  (``_DecodeEngine.chunk_tokens``); the landing offset is a traced
  scalar, so a prompt of any length streams in over ``ceil(L/C)``
  dispatches of the same compiled program.  Only the FINAL chunk's
  masked scatter activates the slot.
- **sampling** — per-slot ``fold_in(key_slot, pos_slot)`` +
  ``categorical`` on that slot's row, matching ``kv_generate``'s
  batch-1 stream for the same seed token-for-token (greedy is argmax).

``PagePool`` is the host-side free-list allocator with REFCOUNTS: the
prefix cache maps one page into many slots' tables, and a page returns
to the free list only when its last owner (slot or cache index) lets go.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import telemetry
from ..base import MXNetError
from ..models.decoding import (_DecodeEngine, _TRACE_LOCK, _kv_requant,
                               _KV_CODE_DTYPE, _KV_SCALE_DTYPE)
from . import schema

__all__ = ["PoolPrograms", "PagePool", "pool_state_init",
           "pool_state_grow", "pool_state_bytes",
           "admit_scratch_bytes"]


# per-slot scalar state bytes, derived from the operand schema's
# SLOT_STATE layout (pos/tok/stop/spec int32 + active bool + PRNG key
# 2x uint32 + deadline float32 = 29) — see pool_state_init, which
# builds the columns in the same declared order
_SLOT_STATE_BYTES = schema.slot_state_bytes()

# meta-row column maps, derived from the same declarations the jitted
# bodies below unpack through (tracelint TL017 holds these bodies to
# the accessors — a hand-written column index is exactly the drift
# that threaded PR-13's deadline and PR-17's spec-depth through four
# scatter sites by eye)
_AM = schema.meta_cols("admit")
_HM = schema.meta_cols("hit")
_CM = schema.meta_cols("chunk")


class PagePool:
    """Host-side page allocator with refcounts (LIFO free list — a just-
    freed page is the hottest candidate for reuse).  Pages are ints in
    ``[0, num_pages)``; the COW prefix cache increfs shared pages into
    many owners, and a page returns to the free list only at refcount
    zero.  Purely host bookkeeping: the device never sees this object,
    only the page-table rows built from it."""

    def __init__(self, num_pages):
        self.num_pages = int(num_pages)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._ref = {}

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def in_use(self):
        return self.num_pages - len(self._free)

    def alloc(self, n):
        """``n`` fresh pages at refcount 1, or ``None`` if the pool
        cannot cover the request (nothing is allocated on failure —
        admission is all-or-nothing so a half-reserved request can
        never deadlock the pool)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def incref(self, page):
        self._ref[page] += 1

    def decref(self, page):
        """Drop one owner; frees the page at refcount zero."""
        r = self._ref[page] - 1
        if r:
            self._ref[page] = r
        else:
            del self._ref[page]
            self._free.append(page)

    def grow(self, new_num):
        """Extend the pool with pages ``[num_pages, new_num)`` (pool
        growth allocates a bigger device array; the new ids join the
        free list)."""
        if new_num < self.num_pages:
            raise MXNetError(f"page pool can only grow: "
                             f"{self.num_pages} -> {new_num}")
        self._free.extend(range(new_num - 1, self.num_pages - 1, -1))
        self.num_pages = int(new_num)


def pool_state_bytes(progs, num_slots=None, num_pages=None):
    """Device bytes of the pool state at ``num_slots`` slots /
    ``num_pages`` pages (defaults: the programs' own geometry; the
    default page count is ``num_slots * MAXP`` — the dense-equivalent
    allotment, so the figure stays LINEAR in the slot count and the
    budget thresholds keep their PR-10 meaning).  Priced at the
    programs' OWN ``kv_dtype`` via ``page_bytes()`` — an int8 pool's
    pages cost codes + per-page scales, not the f32 itemsize.  Pure
    arithmetic, so ``DecodeServer`` can price a growth (or the initial
    pool) BEFORE allocating it; ``tests/test_memory.py`` pins this
    equal to the allocator-reported ``nbytes_of`` of the live state
    for BOTH dtypes."""
    S = progs.S if num_slots is None else int(num_slots)
    npages = S * progs.maxp if num_pages is None else int(num_pages)
    return npages * progs.page_bytes() + S * _SLOT_STATE_BYTES


def admit_scratch_bytes(progs, a_bucket):
    """Transient device bytes of an ``a_bucket``-row admission wave:
    the dense ``(A, Tp)`` prefill scratch cache pair at the model's
    NATIVE cache dtype plus the wave's slot-state rows.  The admit
    program always prefills into a dense float scratch and quantizes
    on the page scatter, so this figure is dtype-INDEPENDENT — under
    ``kv_dtype="int8"`` it deliberately does NOT shrink with
    ``pool_state_bytes`` (which it equals for a native-dtype pool at
    the dense-equivalent page count), keeping the budget clamp honest
    about the admission spike."""
    e = progs.eng
    A = int(a_bucket)
    return 2 * e.NL * A * e.KV * progs.Tp * e.D \
        * jnp.dtype(e.cdtype).itemsize + A * _SLOT_STATE_BYTES


def pool_state_init(progs, device=None):
    """Fresh all-idle pool state for ``progs``: ``(kp, vp, pos, tok,
    active, stop, keys, deadline, spec)`` — the traced-operand set every
    step/admit/hit/chunk/verify executable threads through (the page
    TABLES are not in it: they are host numpy, rebuilt per dispatch).
    ``deadline`` is the per-slot wall-clock retirement budget (seconds
    on the server's monotonic epoch; ``+inf`` = none), checked ON
    DEVICE by the step against its ``now`` operand; ``spec`` is the
    per-slot speculation-depth cap (0 = never speculate) the verify
    program clamps draft acceptance against — riding the slot-state
    vector like keys and deadlines do, so per-request depth never
    shapes a trace.

    Every array is COMMITTED to ``device`` (default: the backend's
    first device).  jit keys its executable cache on each argument's
    committed placement, so an uncommitted ``jnp.zeros`` init state
    would compile one signature for the first step and a SECOND
    (identical-aval) signature once the state is jit outputs — a
    silent ~seconds retrace on the serving hot path at steady state."""
    S = progs.S
    eng = progs.eng
    if device is None:
        device = jax.devices()[0]
    shape = (eng.NL, progs.num_pages, eng.KV, progs.page, eng.D)
    if progs.quant_kv:
        # int8 pool: each of K and V is a (codes, scales) PAIR riding
        # ONE state slot as a pytree — every executable threads, donates
        # and scans it exactly like the single f32 array it replaces
        sshape = (eng.NL, progs.num_pages, eng.KV)
        kpool = (jnp.zeros(shape, _KV_CODE_DTYPE),
                 jnp.zeros(sshape, _KV_SCALE_DTYPE))
        vpool = (jnp.zeros(shape, _KV_CODE_DTYPE),
                 jnp.zeros(sshape, _KV_SCALE_DTYPE))
    else:
        kpool = jnp.zeros(shape, eng.cdtype)
        vpool = jnp.zeros(shape, eng.cdtype)
    state = (kpool,                          # K page pool
             vpool,                          # V page pool
             jnp.zeros((S,), jnp.int32),     # pos: next write index
             jnp.zeros((S,), jnp.int32),     # tok: last sampled
             jnp.zeros((S,), jnp.bool_),     # active
             jnp.zeros((S,), jnp.int32),     # stop: retire position
             jnp.zeros((S, 2), jnp.uint32),  # per-slot PRNG keys
             jnp.full((S,), jnp.inf, jnp.float32),  # per-slot deadline
             jnp.zeros((S,), jnp.int32))     # spec: speculation depth
    return jax.device_put(state, device)


def pool_state_grow(state, new_s, new_pages=None):
    """Pad the slot-axis arrays of ``state`` up to ``new_s`` slots and
    (optionally) the page pools up to ``new_pages`` pages — new lanes
    come up idle, new pages come up zero (the caller hands their ids to
    its ``PagePool``).  Runs eagerly — pool growth happens at a step
    boundary, a handful of times per server lifetime.  NOTE the table
    sentinel moves with the page count: rows must be rebuilt against
    the grown pool before the next dispatch (the server regenerates
    them from its allocator every dispatch, so this is automatic)."""
    kp, vp, pos, tok, active, stop, keys, dl, spec = state
    kp0 = kp[0] if isinstance(kp, tuple) else kp
    grow = new_s - pos.shape[0]
    if grow <= 0:
        raise MXNetError(f"pool can only grow: {pos.shape[0]} -> "
                         f"{new_s}")
    pgrow = 0 if new_pages is None else int(new_pages) - kp0.shape[1]
    if pgrow < 0:
        raise MXNetError(f"page pool can only grow: {kp0.shape[1]} -> "
                         f"{new_pages}")
    pad = lambda a, axis, n: jnp.pad(
        a, [(0, n) if i == axis else (0, 0) for i in range(a.ndim)])
    # int8 pools pad codes AND scales along the shared page axis
    padp = lambda p, n: (pad(p[0], 1, n), pad(p[1], 1, n)) \
        if isinstance(p, tuple) else pad(p, 1, n)
    grown = (padp(kp, pgrow), padp(vp, pgrow), pad(pos, 0, grow),
             pad(tok, 0, grow), pad(active, 0, grow), pad(stop, 0, grow),
             pad(keys, 0, grow),
             # idle-lane deadlines pad as +inf, matching pool_state_init
             jnp.pad(dl, (0, grow), constant_values=jnp.inf),
             pad(spec, 0, grow))
    # committed placement, same contract as pool_state_init
    return jax.device_put(grown, list(kp0.devices())[0])


class PoolPrograms:
    """Compiled decode-step + admission executables for ONE pool size
    (slot count) ``num_slots`` against a ``num_pages``-page pool of
    ``page_size``-token pages (cache horizon ``max_total`` rounded up
    to whole pages).  ``temperature``/``top_k``/``eos_id`` are
    server-level static config (they shape the compiled sampler);
    per-request variation rides in the operands (seed key, stop
    position, page-table rows)."""

    def __init__(self, model, num_slots, max_total, temperature=0.0,
                 top_k=0, eos_id=None, weights="native",
                 telemetry_label=None, page_size=16, num_pages=None,
                 kv_dtype="native"):
        self.model = model
        self.telemetry_label = telemetry_label
        # "native" stores pages at the engine cache dtype (the exact
        # pre-PR behavior); "int8" stores codes + per-page-per-head f32
        # scales, quantized inside the SAME write executables and
        # dequantized inside the scan body on read (lossy — PARITY.md
        # pins the tolerance)
        if kv_dtype not in ("native", "int8"):
            raise MXNetError(f"kv_dtype must be 'native' or 'int8', "
                             f"got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        self.quant_kv = kv_dtype == "int8"
        self.S, self.T = int(num_slots), int(max_total)
        self.page = int(page_size)
        if self.page < 1:
            raise MXNetError(f"page_size must be >= 1, got {self.page}")
        # cache horizon rounded up to whole pages: the step's attention
        # span and every table row cover MAXP pages
        self.Tp = -(-self.T // self.page) * self.page
        self.maxp = self.Tp // self.page
        self.num_pages = self.S * self.maxp if num_pages is None \
            else int(num_pages)
        if self.num_pages < 1:
            raise MXNetError(f"num_pages must be >= 1, "
                             f"got {self.num_pages}")
        # one-past-the-end page id: gathers fill zero, scatters drop
        self.sentinel = self.num_pages
        self.temperature, self.top_k = float(temperature), int(top_k)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.weights = weights
        self.eng = _DecodeEngine(model, self.S, 1, self.Tp, temperature,
                                 top_k, "batched", weights, "off",
                                 "auto")
        if self.eng.mode != "stacked":
            raise MXNetError(
                "slot-pool serving needs the stacked-layer scan decode "
                "step (uniform GPT/Llama stack — see ops/decode_fused."
                "stacked_decode_supported); this model resolved to "
                f"{self.eng.mode!r}.  MXNET_SERVE_SYNC=1 serves it "
                "through the synchronous kv_generate fallback instead.")
        # the server owns the weight operands (engine refs dropped so
        # the cached executables' closures can't pin stale arrays)
        param_vals, q8, _packed, sw = self.eng.take_operands()
        self.operands = (param_vals, q8, sw)
        self._step = None
        self._admits = {}          # (A, P) bucket pair -> jitted fn
        self._hits = {}            # A bucket -> jitted hit-admission fn
        self._chunks = {}          # C bucket -> jitted chunk-prefill fn
        self._verifies = {}        # k bucket -> jitted verify fn

    def page_bytes(self):
        """Device bytes of ONE page across all layers, K and V pools
        together — the pricing unit ``pool_state_bytes`` scales.  An
        int8 page costs its codes (1 byte/element) plus one f32 scale
        per (layer, KV head) for each of K and V — the ~4x shrink vs a
        float32 pool is what converts an HBM budget into ~2x resident
        sequences at equal bytes."""
        e = self.eng
        if self.quant_kv:
            return schema.kv_page_int8_bytes(e.NL, e.KV, self.page,
                                             e.D)
        return 2 * e.NL * e.KV * self.page * e.D \
            * jnp.dtype(e.cdtype).itemsize

    def pages_for(self, total_len):
        """Pages a sequence of ``total_len`` cached positions needs."""
        return -(-int(total_len) // self.page)

    # -- sampling ------------------------------------------------------- #
    def _sample_slots(self, keys, logits, pos):
        """Per-slot next token: slot ``i`` draws with
        ``fold_in(keys[i], pos[i])`` over its own logits row — the exact
        key/categorical stream ``kv_generate(seed=...)`` runs at batch 1,
        so a served request reproduces the offline stream.  The
        temperature/top_k prep is ``_DecodeEngine._sample_logits``, the
        SAME prep the offline sampler draws from."""
        lg = self.eng._sample_logits(logits)
        if lg is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def draw(key, row, p):
            return jax.random.categorical(
                jax.random.fold_in(key, p), row[None, :], axis=-1)[0]

        return jax.vmap(draw)(keys, lg, pos).astype(jnp.int32)

    def _retire_flags(self, active, nxt, newpos, stop, now=None,
                      deadline=None):
        done = active & (newpos >= stop)
        if self.eos_id is not None:
            done = done | (active & (nxt == self.eos_id))
        if now is not None:
            # wall-clock deadline expiry, folded into the SAME done
            # mask as EOS/budget: retirement stays a masked device-side
            # update, never an extra dispatch (inf = no deadline)
            done = done | (active & (now >= deadline))
        return done

    # -- the decode step ------------------------------------------------ #
    def step_fn(self):
        """The jitted pool step (cached): ``step(param_vals, q8, sw,
        now, pt, kp, vp, pos, tok, active, stop, keys, deadline)`` → new
        state + ``(emit_tok, emitted, done)`` readback arrays.  ``now``
        is the host's monotonic clock (server-epoch seconds, a float32
        scalar operand refreshed per dispatch); ``pt`` is the ``(S,
        MAXP)`` int32 page-table block — BOTH are operands, not
        constants, so neither clock ticks nor page churn ever retrace.
        Page pools are donated — steady-state serving is one
        donated-buffer executable dispatch per emitted token wave."""
        if self._step is not None:
            return self._step
        from ..gluon.parameter import params_swapped

        eng = self
        deng = self.eng
        page = self.page

        def step(param_vals, q8, sw, now, pt, kp, vp, pos, tok, active,
                 stop, keys, dl, spec):
            with _TRACE_LOCK, params_swapped(deng.params, param_vals):
                logits, kp, vp = deng.pool_token_paged(
                    tok, pos, kp, vp, pt, page, sw, q8)
                nxt = eng._sample_slots(keys, logits, pos)
            nxt = jnp.where(active, nxt, tok)
            newpos = jnp.where(active, pos + 1, pos)
            done = eng._retire_flags(active, nxt, newpos, stop, now, dl)
            emitted = active
            new_state = (kp, vp, newpos, nxt, active & ~done, stop,
                         keys, dl, spec)
            return new_state, (nxt, emitted, done)

        self._step = telemetry.instrument_jit(
            jax.jit(step, donate_argnums=schema.jit_donate("step", step)),
            "serve.step",
            key=(self.telemetry_label, self.S),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "num_pages": self.num_pages,
                    "cache_bytes": self.num_pages * self.page_bytes()})
        return self._step

    # -- admission ------------------------------------------------------ #
    def admit_fn(self, a_bucket, p_bucket):
        """The jitted BATCHED admission program for a wave of up to
        ``a_bucket`` prompts right-padded to ``p_bucket`` tokens (cached
        per ``(A, P)`` bucket pair): ``admit(param_vals, prompts
        (A, P) int32, meta (A, 6) int32 rows = [valid, true_len, slot,
        stop_pos, seed, spec_depth], dls (A,) float32 per-row deadlines,
        pages (A, NPB) int32 reserved-page rows, zpages (A, MAXP) int32
        full reserved rows (sentinel-padded; int8 pools zero these
        pages' SCALES before anything writes — see below), kp, vp, pos,
        tok, active, stop, keys, dl, spec)`` → new state + ``(first_tok
        (A,), done (A,))``.

        ONE causal prefill over the whole block fills a dense ``(A,
        Ppad)`` scratch cache, which lands in the wave's RESERVED PAGES
        via one masked page scatter: row ``i``'s page ``j`` goes to
        pool page ``pages[i, j]``; idle rows and unreserved tail pages
        carry the sentinel and are DROPPED, so a half-full wave (or a
        short prompt) reuses the same compiled program.  The first
        continuation token of each row is sampled at its own
        ``true_len - 1``; a request whose budget is a single token (or
        whose first token is EOS) comes back ``done`` and never
        occupies a step lane.  Admitting a wave of k requests is one
        H2D of the prompt block + meta + page rows and ONE executable
        dispatch, not k of either."""
        key2 = (int(a_bucket), int(p_bucket))
        fn = self._admits.get(key2)
        if fn is not None:
            return fn
        A, P = key2
        if not 0 < P <= self.T:
            raise MXNetError(f"prompt bucket {P} outside cache "
                             f"length {self.T}")
        if A < 1:
            raise MXNetError(f"admission bucket {A} must be >= 1")
        from ..gluon.parameter import params_swapped

        page = self.page
        ppad = -(-P // page) * page     # prompt bucket in whole pages
        npb = ppad // page
        peng = _DecodeEngine(self.model, A, P, ppad,
                             self.temperature, self.top_k, "batched",
                             self.weights, "off", "auto")
        peng.take_operands()    # server-held operands are the only refs
        NL, KV, D = peng.NL, peng.KV, peng.D

        def admit(param_vals, prompts, meta, dls, pages, zpages, kp, vp,
                  pos, tok, active, stop, keys, dl, spec):
            valid = meta[:, _AM["valid"]] != 0
            true_len = meta[:, _AM["true_len"]]
            slot = meta[:, _AM["slot"]]
            stop_pos = meta[:, _AM["stop_pos"]]
            seed = meta[:, _AM["seed"]]
            spec_d = meta[:, _AM["spec_depth"]]
            keys_a = jax.vmap(jax.random.PRNGKey)(seed)       # (A, 2)
            with _TRACE_LOCK, params_swapped(peng.params, param_vals):
                ck1, cv1 = peng.zero_caches()
                logits, ck1, cv1 = peng.prefill_batch(
                    prompts, ck1, cv1, last_index=true_len - 1)
                first = self._sample_slots(keys_a, logits,
                                           true_len - 1)
            done = stop_pos <= true_len
            if self.eos_id is not None:
                done = done | (first == self.eos_id)
            # page scatter: the dense (A, Ppad) scratch splits into A*NPB
            # page-shaped rows that land at their reserved pool pages in
            # one masked scatter per array (sentinel rows DROP)
            tgt_pg = pages.reshape(A * npb)
            if self.quant_kv:
                # the padded tail's garbage columns are unreachable in
                # the f32 pool but would poison the per-page SCALES
                # here — zero them before the per-page quantization
                colmask = jnp.arange(ppad, dtype=jnp.int32)[None] \
                    < true_len[:, None]                     # (A, ppad)
                ck1 = jnp.where(colmask[None, :, None, :, None],
                                ck1, 0)
                cv1 = jnp.where(colmask[None, :, None, :, None],
                                cv1, 0)
            c1 = ck1.reshape(NL, A, KV, npb, page, D) \
                    .transpose(0, 1, 3, 2, 4, 5) \
                    .reshape(NL, A * npb, KV, page, D)
            v1 = cv1.reshape(NL, A, KV, npb, page, D) \
                    .transpose(0, 1, 3, 2, 4, 5) \
                    .reshape(NL, A * npb, KV, page, D)
            if self.quant_kv:
                # fresh whole pages: plain per-page quantization (no
                # floor — nothing lived in these pages), then ONE
                # masked scatter each for codes and scales
                qc1, sc1 = _kv_requant(c1, 0.0)
                qv1, sv1 = _kv_requant(v1, 0.0)
                (kpc, kps), (vpc, vps) = kp, vp
                # recycled-page reset: the pool free list is host-only
                # bookkeeping, so a reallocated page still carries its
                # previous tenant's codes AND scale.  A zero SCALE is a
                # full reset — stale codes dequantize to exact zeros
                # and the first RMW requantizes from floor 0.0, so the
                # old tenant's dynamic range can never ratchet the new
                # tenant's scale.  ``zpages`` holds every page the wave
                # reserved (decode-frontier pages included — those are
                # first WRITTEN by the step/verify RMWs); the prompt
                # pages' scales are immediately overwritten by the
                # scatter below.  Sentinel entries DROP.
                zf = zpages.reshape(A * zpages.shape[1])
                kps = kps.at[:, zf].set(0.0, mode="drop")
                vps = vps.at[:, zf].set(0.0, mode="drop")
                kp = (kpc.at[:, tgt_pg].set(qc1, mode="drop"),
                      kps.at[:, tgt_pg].set(sc1, mode="drop"))
                vp = (vpc.at[:, tgt_pg].set(qv1, mode="drop"),
                      vps.at[:, tgt_pg].set(sv1, mode="drop"))
            else:
                kp = kp.at[:, tgt_pg].set(c1, mode="drop")
                vp = vp.at[:, tgt_pg].set(v1, mode="drop")
            # masked slot-state scatter: invalid rows target slot S
            # (out of bounds) and drop; valid rows carry distinct
            # host-assigned slots
            tgt = jnp.where(valid, slot, self.S)
            pos = pos.at[tgt].set(true_len, mode="drop")
            tok = tok.at[tgt].set(first, mode="drop")
            active = active.at[tgt].set(~done, mode="drop")
            stop = stop.at[tgt].set(stop_pos, mode="drop")
            keys = keys.at[tgt].set(keys_a, mode="drop")
            dl = dl.at[tgt].set(dls, mode="drop")
            spec = spec.at[tgt].set(spec_d, mode="drop")
            new_state = (kp, vp, pos, tok, active, stop, keys, dl, spec)
            return new_state, (first, done)

        fn = telemetry.instrument_jit(
            jax.jit(admit,
                    donate_argnums=schema.jit_donate("admit", admit)),
            "serve.admit",
            key=(self.telemetry_label, self.S, A, P),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "a_bucket": A, "p_bucket": P,
                    # the A-lane prefill cache pair — the admit
                    # program's transient scratch the budget check
                    # prices (pool_state_bytes(progs, A))
                    "cache_bytes": peng.cache_bytes()})
        self._admits[key2] = fn
        return fn

    def admit_hit_fn(self, a_bucket):
        """The jitted PREFIX-CACHE-HIT admission program for up to
        ``a_bucket`` rows (cached per bucket): ``hit(meta (A, 7) int32
        rows = [valid, true_len, slot, stop_pos, seed, last_tok,
        spec_depth], dls (A,), src (A,), dst (A,), zpages (A, MAXP)
        int32 fresh-owned-page rows (sentinel-padded; int8 pools zero
        these pages' SCALES), kp, vp, pos, tok, active, stop, keys, dl,
        spec)`` → new state (no readback: a hit emits nothing at
        admission).

        NO model forward runs: the host has already mapped the shared
        prefix pages into the slot's table row, so admission is a
        masked slot-state scatter — the slot enters at ``pos = L - 1``
        with ``tok`` = the last prompt token, and the next regular STEP
        recomputes that position (writing its K/V through the table and
        sampling with ``fold_in(key, L - 1)``, the exact admission key
        of the batched path — hit and miss token streams match while a
        hit's TTFT is one decode step and ZERO prefill dispatches).
        ``src``/``dst`` carry at most one copy-on-write page copy per
        row (needed only when the prompt ends exactly on a shared page
        boundary, where the recompute-write would land in a shared
        page); rows without a copy carry the sentinel on both sides
        (gather fills zeros, scatter drops)."""
        A = int(a_bucket)
        fn = self._hits.get(A)
        if fn is not None:
            return fn
        if A < 1:
            raise MXNetError(f"admission bucket {A} must be >= 1")

        def hit(meta, dls, src, dst, zpages, kp, vp, pos, tok, active,
                stop, keys, dl, spec):
            valid = meta[:, _HM["valid"]] != 0
            true_len = meta[:, _HM["true_len"]]
            slot = meta[:, _HM["slot"]]
            stop_pos = meta[:, _HM["stop_pos"]]
            seed = meta[:, _HM["seed"]]
            last_tok = meta[:, _HM["last_tok"]]
            spec_d = meta[:, _HM["spec_depth"]]
            keys_a = jax.vmap(jax.random.PRNGKey)(seed)       # (A, 2)
            # copy-on-write boundary pages: one gather + one masked
            # scatter covers the whole wave's copies.  An int8 pool
            # copies codes AND scales together — a page's quantization
            # grid is part of its identity, refcounted as one unit.
            if self.quant_kv:
                (kpc, kps), (vpc, vps) = kp, vp
                kcb = kpc.at[:, src].get(mode="fill", fill_value=0)
                ksb = kps.at[:, src].get(mode="fill", fill_value=0)
                vcb = vpc.at[:, src].get(mode="fill", fill_value=0)
                vsb = vps.at[:, src].get(mode="fill", fill_value=0)
                # recycled-page reset (see admit_fn): zero the SCALES
                # of every freshly-owned page in the wave — including
                # each row's decode-frontier pages and the COW dst —
                # AFTER the src gathers above (a src page can double as
                # another row's fresh page when an eviction inside this
                # same wave recycled it) and BEFORE the dst scatter
                # below re-lands the copied scale.
                zf = zpages.reshape(-1)
                kps = kps.at[:, zf].set(0.0, mode="drop")
                vps = vps.at[:, zf].set(0.0, mode="drop")
                kp = (kpc.at[:, dst].set(kcb, mode="drop"),
                      kps.at[:, dst].set(ksb, mode="drop"))
                vp = (vpc.at[:, dst].set(vcb, mode="drop"),
                      vps.at[:, dst].set(vsb, mode="drop"))
            else:
                kblk = kp.at[:, src].get(mode="fill", fill_value=0)
                vblk = vp.at[:, src].get(mode="fill", fill_value=0)
                kp = kp.at[:, dst].set(kblk, mode="drop")
                vp = vp.at[:, dst].set(vblk, mode="drop")
            tgt = jnp.where(valid, slot, self.S)
            pos = pos.at[tgt].set(true_len - 1, mode="drop")
            tok = tok.at[tgt].set(last_tok, mode="drop")
            active = active.at[tgt].set(valid, mode="drop")
            stop = stop.at[tgt].set(stop_pos, mode="drop")
            keys = keys.at[tgt].set(keys_a, mode="drop")
            dl = dl.at[tgt].set(dls, mode="drop")
            spec = spec.at[tgt].set(spec_d, mode="drop")
            return (kp, vp, pos, tok, active, stop, keys, dl, spec)

        fn = telemetry.instrument_jit(
            jax.jit(hit, donate_argnums=schema.jit_donate("hit", hit)),
            "serve.admit_hit",
            key=(self.telemetry_label, self.S, A),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "a_bucket": A})
        self._hits[A] = fn
        return fn

    def chunk_fn(self, c_bucket):
        """The jitted CHUNKED-PREFILL program for one ``C``-token slice
        of a single prompt (cached per chunk bucket): ``chunk(
        param_vals, q8, sw, toks (C,) int32, meta (8,) int32 =
        [final, slot, true_len, stop_pos, seed, nlast, off,
        spec_depth], dls scalar f32, ptrow (MAXP,) int32, zrow (MAXP,)
        int32 pages to scale-reset before the RMW (the slot's freshly
        allocated pages on its FIRST chunk, sentinel afterward), kp,
        vp, pos, tok, active, stop, keys, dl, spec)`` → new state +
        ``(first_tok, done)`` scalars.

        The slice occupies absolute positions ``off .. off+C-1`` of the
        slot whose page-table row is ``ptrow`` (``off`` is TRACED — one
        compiled program per chunk length serves every landing offset,
        so a prompt of any length streams in over ``ceil(L/C)``
        dispatches with no retrace).  Intermediate chunks pass
        ``final = 0``: their state scatter targets slot ``S`` and
        DROPS, so the slot stays invisible to the step until the final
        chunk samples the first continuation token (at ``true_len - 1``
        with ``fold_in(PRNGKey(seed), true_len - 1)`` — the batched
        path's exact admission key) and activates it.  Also the
        prefix-cache PARTIAL-hit suffix path: with shared pages mapped
        for ``off`` tokens, the same program fills only the divergent
        tail."""
        C = int(c_bucket)
        fn = self._chunks.get(C)
        if fn is not None:
            return fn
        if not 0 < C <= self.Tp:
            raise MXNetError(f"chunk bucket {C} outside cache "
                             f"length {self.Tp}")
        from ..gluon.parameter import params_swapped

        deng = self.eng
        page = self.page

        def chunk(param_vals, q8, sw, toks, meta, dls, ptrow, zrow, kp,
                  vp, pos, tok, active, stop, keys, dl, spec):
            final = meta[_CM["final"]]
            slot = meta[_CM["slot"]]
            true_len = meta[_CM["true_len"]]
            stop_pos = meta[_CM["stop_pos"]]
            seed = meta[_CM["seed"]]
            nlast = meta[_CM["nlast"]]
            off = meta[_CM["off"]]
            spec_d = meta[_CM["spec_depth"]]
            key1 = jax.random.PRNGKey(seed)                   # (2,)
            if self.quant_kv:
                # recycled-page reset (see admit_fn): the chunk RMW
                # gathers each window page's scale as its requant
                # FLOOR, so stale scales must be zeroed before the
                # first chunk touches the slot's pages.  The host sends
                # the freshly-allocated rows in ``zrow`` on the first
                # chunk only (all-sentinel afterward — later chunks
                # must keep the ratchet of earlier ones).
                (kpc, kps), (vpc, vps) = kp, vp
                kp = (kpc, kps.at[:, zrow].set(0.0, mode="drop"))
                vp = (vpc, vps.at[:, zrow].set(0.0, mode="drop"))
            with _TRACE_LOCK, params_swapped(deng.params, param_vals):
                logits, kp, vp = deng.chunk_tokens(
                    toks, off, nlast, ptrow, page, kp, vp, sw, q8)
                first = self._sample_slots(key1[None], logits,
                                           (true_len - 1)[None])[0]
            done = stop_pos <= true_len
            if self.eos_id is not None:
                done = done | (first == self.eos_id)
            # scalar masked scatter: intermediate chunks target slot S
            # and drop — only the final chunk activates the slot
            tgt = jnp.where(final != 0, slot, self.S)
            pos = pos.at[tgt].set(true_len, mode="drop")
            tok = tok.at[tgt].set(first, mode="drop")
            active = active.at[tgt].set((final != 0) & ~done,
                                        mode="drop")
            stop = stop.at[tgt].set(stop_pos, mode="drop")
            keys = keys.at[tgt].set(key1, mode="drop")
            dl = dl.at[tgt].set(dls, mode="drop")
            spec = spec.at[tgt].set(spec_d, mode="drop")
            new_state = (kp, vp, pos, tok, active, stop, keys, dl, spec)
            return new_state, (first, done)

        fn = telemetry.instrument_jit(
            jax.jit(chunk,
                    donate_argnums=schema.jit_donate("chunk", chunk)),
            "serve.chunk",
            key=(self.telemetry_label, self.S, C),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "c_bucket": C,
                    # one slot's dense gather scratch per layer slice
                    "cache_bytes": self.eng.cache_bytes() // self.S})
        self._chunks[C] = fn
        return fn

    def verify_fn(self, k_bucket):
        """The jitted DRAFT-AND-VERIFY program for up to ``k_bucket``
        drafted tokens per slot (cached per k bucket, the PR-8 ladder
        discipline — compile count is bounded by the pinned k ladder,
        and accept/reject churn only changes operand VALUES):
        ``verify(param_vals, q8, sw, now, pt, drafts (S, k) int32,
        nd (S,) int32 drafts-actually-proposed per slot, kp, vp, pos,
        tok, active, stop, keys, dl, spec)`` → new state +
        ``(out (S, K), adv (S,), done (S,))``.

        ONE pool-step-shaped dispatch scores ``K = k + 1`` positions
        per slot (column 0 is the slot's last emitted token, not yet
        attended — a slot with ``nd = 0`` drafts runs a plain step
        through it): ``out[s, j]`` is the greedy token the plain step
        path would emit after position ``pos[s] + j``, so the device
        accepts the longest prefix where ``out[:, :-1]`` matches the
        drafts (clamped by ``nd``, the slot-state ``spec`` cap, EOS,
        and the slot's remaining ``stop`` budget) and advances
        ``adv = accepted + 1`` positions.  The block's K/V columns are
        already in the paged pool; a REJECTED tail needs no undo — its
        columns sit past the advanced ``pos``, masked off causally and
        overwritten before the next attend, and pages were reserved
        for the full budget at admission, so rollback is the length
        update alone (never a copy, never a refcount).  Greedy only:
        acceptance compares argmax tokens, which is exact for
        ``temperature == 0`` — the server keeps sampled slots on the
        plain depth-1 step (rejection sampling is out of scope)."""
        k = int(k_bucket)
        fn = self._verifies.get(k)
        if fn is not None:
            return fn
        if k < 1:
            raise MXNetError(f"verify bucket {k} must be >= 1")
        if self.temperature != 0.0:
            raise MXNetError(
                "draft-and-verify acceptance is exact only for greedy "
                f"decoding; temperature={self.temperature} slots must "
                "run the plain step (rejection sampling is out of "
                "scope for v1)")
        from ..gluon.parameter import params_swapped

        eng = self
        deng = self.eng
        page = self.page
        S, K = self.S, k + 1

        def verify(param_vals, q8, sw, now, pt, drafts, nd, kp, vp,
                   pos, tok, active, stop, keys, dl, spec):
            toks = jnp.concatenate([tok[:, None], drafts], axis=1)
            with _TRACE_LOCK, params_swapped(deng.params, param_vals):
                logits, kp, vp = deng.pool_verify_paged(
                    toks, pos, pt, page, kp, vp, sw, q8)
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S,K)
            # longest accepted prefix: draft j survives iff every
            # draft 0..j matched the model's own emission AND j is
            # inside both the proposed count and the slot's spec cap
            lim = jnp.minimum(nd, spec)
            ok = (out[:, :-1] == drafts) & \
                (jnp.arange(K - 1, dtype=jnp.int32)[None, :] <
                 lim[:, None])
            acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1),
                          axis=1)
            adv = acc + 1
            if self.eos_id is not None:
                # an emitted EOS ends the stream: nothing past the
                # first one may be emitted, exactly like the step path
                iK = jnp.arange(K, dtype=jnp.int32)
                first_eos = jnp.min(
                    jnp.where(out == self.eos_id, iK[None, :], K),
                    axis=1)
                adv = jnp.minimum(adv, first_eos + 1)
            # never advance past the slot's stop position (its last
            # block columns were computed but are not emitted)
            adv = jnp.minimum(adv, jnp.maximum(stop - pos, 1))
            adv = jnp.where(active, adv, 0)
            nxt = jnp.where(
                active,
                out[jnp.arange(S), jnp.maximum(adv, 1) - 1], tok)
            newpos = pos + adv
            done = eng._retire_flags(active, nxt, newpos, stop, now,
                                     dl)
            new_state = (kp, vp, newpos, nxt, active & ~done, stop,
                         keys, dl, spec)
            return new_state, (out, adv, done)

        fn = telemetry.instrument_jit(
            jax.jit(verify,
                    donate_argnums=schema.jit_donate("verify", verify)),
            "serve.verify",
            key=(self.telemetry_label, self.S, K),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "k_bucket": k,
                    # the verify block widens the step's dense gather
                    # scratch K-fold at the attention tail
                    "cache_bytes": self.eng.cache_bytes()})
        self._verifies[k] = fn
        return fn
