"""Slot-pool decode programs — the device side of the continuous-
batching server (``mxnet_tpu.serve.server``).

One resident ``(NL, S, KV, T, D)`` K/V-cache pair is shared by all
in-flight sequences; per-slot position / last-token / active / stop /
sampling-key / wall-clock-deadline state rides as TRACED OPERANDS next
to it, so admission and retirement — including deadline expiry against
the step's ``now`` operand (ISSUE 13) — are device-side masked updates:
no recompile, no host sync in the step.  Three compiled units per pool
size ``S``:

- **step** — ``_DecodeEngine.pool_token`` (the stacked-layer scan with
  per-slot positions) + per-slot sampling + retirement flags, jitted
  with the caches donated: ONE executable dispatch per decode step, the
  same one-executable discipline as ``kv_generate``'s scan
  (``tests/test_serve.py`` pins the dispatch count).
- **admit(A_bucket, P_bucket)** — ONE causal prefill over an ``(A, P)``
  block of right-padded prompts (compiled per bucket PAIR from pinned
  ladders, so admission cost stays a handful of programs): up to ``A``
  pending requests' K/V streams are written into their assigned pool
  slots in one masked device-side scatter, and the ``A`` first tokens +
  done flags come back in one readback.  Rows beyond the wave are
  masked no-ops (their scatter target is out of bounds and DROPPED), so
  a partially full wave reuses the same program — admitting an arrival
  wave of k requests is O(1) dispatches, not O(k).  Each padded tail's
  cache columns are garbage but UNREACHABLE: a decode step at position
  ``q`` writes its own column before attending, so every attended
  column was produced by this sequence.
- **sampling** — per-slot ``fold_in(key_slot, pos_slot)`` +
  ``categorical`` on that slot's row, matching ``kv_generate``'s
  batch-1 stream for the same seed token-for-token (greedy is argmax).

Retired slots keep computing (their lanes are masked in the outputs);
their cache writes land at the stale position and are overwritten on
the next admission.  That wasted lane is the occupancy cost the
benchmark measures — the alternative (reshaping the batch) retraces.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import telemetry
from ..base import MXNetError
from ..models.decoding import _DecodeEngine, _TRACE_LOCK

__all__ = ["PoolPrograms", "pool_state_init", "pool_state_grow",
           "pool_state_bytes"]


# per-slot scalar state bytes: pos/tok/stop int32 (12) + active bool (1)
# + PRNG key 2x uint32 (8) + deadline float32 (4) — see pool_state_init
_SLOT_STATE_BYTES = 25


def pool_state_bytes(eng, num_slots=None):
    """Device bytes of the pool state at ``num_slots`` slots (default:
    the engine's own slot count) — the K/V cache pair plus the
    per-slot scalar vectors.  Pure arithmetic from the engine's
    geometry, so the budget check in ``DecodeServer`` can price a
    growth (or the initial pool) BEFORE allocating it.  The cache term
    is ``_DecodeEngine.cache_bytes`` rescaled to ``num_slots`` lanes —
    ONE formula shared with the compile events' ``cache_bytes`` field,
    so the budget threshold cannot drift from what is reported."""
    S = eng.B if num_slots is None else int(num_slots)
    cache = (eng.cache_bytes() // eng.B) * S
    return cache + S * _SLOT_STATE_BYTES


def pool_state_init(eng, device=None):
    """Fresh all-idle pool state for a ``PoolPrograms``' engine:
    ``(ck, cv, pos, tok, active, stop, keys, deadline)`` — the
    traced-operand set every step/admit executable threads through.
    ``deadline`` is the per-slot wall-clock retirement budget (seconds
    on the server's monotonic epoch; ``+inf`` = none), checked ON
    DEVICE by the step against its ``now`` operand — deadline expiry
    is a masked retirement exactly like EOS/budget, never an extra
    dispatch (ISSUE 13).

    Every array is COMMITTED to ``device`` (default: the backend's
    first device).  jit keys its executable cache on each argument's
    committed placement, so an uncommitted ``jnp.zeros`` init state
    would compile one signature for the first step and a SECOND
    (identical-aval) signature once the state is jit outputs — a
    silent ~seconds retrace on the serving hot path at steady state."""
    S = eng.B
    if device is None:
        device = jax.devices()[0]
    ck, cv = eng.zero_caches()
    state = (ck, cv,
             jnp.zeros((S,), jnp.int32),          # pos: next write index
             jnp.zeros((S,), jnp.int32),          # tok: last sampled
             jnp.zeros((S,), jnp.bool_),          # active
             jnp.zeros((S,), jnp.int32),          # stop: retire position
             jnp.zeros((S, 2), jnp.uint32),       # per-slot PRNG keys
             jnp.full((S,), jnp.inf, jnp.float32))  # per-slot deadline
    return jax.device_put(state, device)


def pool_state_grow(state, new_s):
    """Pad every slot-axis array of ``state`` up to ``new_s`` slots (the
    new lanes come up idle).  Runs eagerly — pool growth happens at a
    step boundary, a handful of times per server lifetime."""
    ck, cv, pos, tok, active, stop, keys, dl = state
    grow = new_s - ck.shape[1]
    if grow <= 0:
        raise MXNetError(f"pool can only grow: {ck.shape[1]} -> {new_s}")
    pad = lambda a, axis: jnp.pad(
        a, [(0, grow) if i == axis else (0, 0) for i in range(a.ndim)])
    grown = (pad(ck, 1), pad(cv, 1), pad(pos, 0), pad(tok, 0),
             pad(active, 0), pad(stop, 0), pad(keys, 0),
             # idle-lane deadlines pad as +inf, matching pool_state_init
             jnp.pad(dl, (0, grow), constant_values=jnp.inf))
    # committed placement, same contract as pool_state_init
    return jax.device_put(grown, list(ck.devices())[0])


class PoolPrograms:
    """Compiled decode-step + per-bucket admission executables for ONE
    pool size (slot count) ``num_slots`` against a ``max_total``-column
    cache.  ``temperature``/``top_k``/``eos_id`` are server-level static
    config (they shape the compiled sampler); per-request variation
    rides in the operands (seed key, stop position)."""

    def __init__(self, model, num_slots, max_total, temperature=0.0,
                 top_k=0, eos_id=None, weights="native",
                 telemetry_label=None):
        self.model = model
        self.telemetry_label = telemetry_label
        self.S, self.T = int(num_slots), int(max_total)
        self.temperature, self.top_k = float(temperature), int(top_k)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.weights = weights
        self.eng = _DecodeEngine(model, self.S, 1, self.T, temperature,
                                 top_k, "batched", weights, "off",
                                 "auto")
        if self.eng.mode != "stacked":
            raise MXNetError(
                "slot-pool serving needs the stacked-layer scan decode "
                "step (uniform GPT/Llama stack — see ops/decode_fused."
                "stacked_decode_supported); this model resolved to "
                f"{self.eng.mode!r}.  MXNET_SERVE_SYNC=1 serves it "
                "through the synchronous kv_generate fallback instead.")
        # the server owns the weight operands (engine refs dropped so
        # the cached executables' closures can't pin stale arrays)
        param_vals, q8, _packed, sw = self.eng.take_operands()
        self.operands = (param_vals, q8, sw)
        self._step = None
        self._admits = {}          # (A, P) bucket pair -> jitted fn

    # -- sampling ------------------------------------------------------- #
    def _sample_slots(self, keys, logits, pos):
        """Per-slot next token: slot ``i`` draws with
        ``fold_in(keys[i], pos[i])`` over its own logits row — the exact
        key/categorical stream ``kv_generate(seed=...)`` runs at batch 1,
        so a served request reproduces the offline stream.  The
        temperature/top_k prep is ``_DecodeEngine._sample_logits``, the
        SAME prep the offline sampler draws from."""
        lg = self.eng._sample_logits(logits)
        if lg is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def draw(key, row, p):
            return jax.random.categorical(
                jax.random.fold_in(key, p), row[None, :], axis=-1)[0]

        return jax.vmap(draw)(keys, lg, pos).astype(jnp.int32)

    def _retire_flags(self, active, nxt, newpos, stop, now=None,
                      deadline=None):
        done = active & (newpos >= stop)
        if self.eos_id is not None:
            done = done | (active & (nxt == self.eos_id))
        if now is not None:
            # wall-clock deadline expiry, folded into the SAME done
            # mask as EOS/budget: retirement stays a masked device-side
            # update, never an extra dispatch (inf = no deadline)
            done = done | (active & (now >= deadline))
        return done

    # -- the decode step ------------------------------------------------ #
    def step_fn(self):
        """The jitted pool step (cached): ``step(param_vals, q8, sw,
        now, ck, cv, pos, tok, active, stop, keys, deadline)`` → new
        state + ``(emit_tok, emitted, done)`` readback arrays.  ``now``
        is the host's monotonic clock (server-epoch seconds, a float32
        scalar operand refreshed per dispatch — an operand, not a
        constant, so it never retraces).  Caches are donated —
        steady-state serving is one donated-buffer executable dispatch
        per emitted token wave."""
        if self._step is not None:
            return self._step
        from ..gluon.parameter import params_swapped

        eng = self.eng

        def step(param_vals, q8, sw, now, ck, cv, pos, tok, active,
                 stop, keys, dl):
            with _TRACE_LOCK, params_swapped(eng.params, param_vals):
                logits, ck, cv = eng.pool_token(tok, pos, ck, cv, sw,
                                                q8)
                nxt = self._sample_slots(keys, logits, pos)
            nxt = jnp.where(active, nxt, tok)
            newpos = jnp.where(active, pos + 1, pos)
            done = self._retire_flags(active, nxt, newpos, stop, now,
                                      dl)
            emitted = active
            new_state = (ck, cv, newpos, nxt, active & ~done, stop,
                         keys, dl)
            return new_state, (nxt, emitted, done)

        self._step = telemetry.instrument_jit(
            jax.jit(step, donate_argnums=(4, 5)), "serve.step",
            key=(self.telemetry_label, self.S),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "cache_bytes": self.eng.cache_bytes()})
        return self._step

    # -- admission ------------------------------------------------------ #
    def admit_fn(self, a_bucket, p_bucket):
        """The jitted BATCHED admission program for a wave of up to
        ``a_bucket`` prompts right-padded to ``p_bucket`` tokens (cached
        per ``(A, P)`` bucket pair): ``admit(param_vals, prompts
        (A, P) int32, meta (A, 5) int32 rows = [valid, true_len, slot,
        stop_pos, seed], dls (A,) float32 per-row deadlines, ck, cv,
        pos, tok, active, stop, keys, dl)`` → new state +
        ``(first_tok (A,), done (A,))``.

        ONE causal prefill over the whole block fills every admitted
        slot's cache columns [0, P) via a masked device-side scatter
        (row ``i`` lands in pool slot ``meta[i, 2]``; rows with
        ``valid == 0`` aim at slot index ``S`` — out of bounds — and
        are DROPPED, so a half-full wave is a no-op on the idle rows
        and reuses the same compiled program).  The first continuation
        token of each row is sampled at its own ``true_len - 1``
        (per-row last index through ``prefill_batch``); a request whose
        budget is a single token (or whose first token is EOS) comes
        back ``done`` and never occupies a step lane.  Per-request
        scalars ride in ONE packed ``(A, 5)`` block and the per-row
        PRNG keys are derived on device — admitting a wave of k
        requests is one H2D of the prompt block + meta and ONE
        executable dispatch, not k of either."""
        key2 = (int(a_bucket), int(p_bucket))
        fn = self._admits.get(key2)
        if fn is not None:
            return fn
        A, P = key2
        if not 0 < P <= self.T:
            raise MXNetError(f"prompt bucket {P} outside cache "
                             f"length {self.T}")
        if A < 1:
            raise MXNetError(f"admission bucket {A} must be >= 1")
        from ..gluon.parameter import params_swapped

        peng = _DecodeEngine(self.model, A, P, self.T,
                             self.temperature, self.top_k, "batched",
                             self.weights, "off", "auto")
        peng.take_operands()    # server-held operands are the only refs

        def admit(param_vals, prompts, meta, dls, ck, cv, pos, tok,
                  active, stop, keys, dl):
            valid = meta[:, 0] != 0
            true_len, slot, stop_pos, seed = (meta[:, 1], meta[:, 2],
                                              meta[:, 3], meta[:, 4])
            keys_a = jax.vmap(jax.random.PRNGKey)(seed)       # (A, 2)
            with _TRACE_LOCK, params_swapped(peng.params, param_vals):
                ck1, cv1 = peng.zero_caches()
                logits, ck1, cv1 = peng.prefill_batch(
                    prompts, ck1, cv1, last_index=true_len - 1)
                first = self._sample_slots(keys_a, logits,
                                           true_len - 1)
            done = stop_pos <= true_len
            if self.eos_id is not None:
                done = done | (first == self.eos_id)
            # masked scatter: invalid rows target slot S (out of
            # bounds) and drop; valid rows carry distinct host-assigned
            # slots, so the whole wave lands in one scatter per array
            tgt = jnp.where(valid, slot, self.S)
            ck = ck.at[:, tgt].set(ck1, mode="drop")
            cv = cv.at[:, tgt].set(cv1, mode="drop")
            pos = pos.at[tgt].set(true_len, mode="drop")
            tok = tok.at[tgt].set(first, mode="drop")
            active = active.at[tgt].set(~done, mode="drop")
            stop = stop.at[tgt].set(stop_pos, mode="drop")
            keys = keys.at[tgt].set(keys_a, mode="drop")
            dl = dl.at[tgt].set(dls, mode="drop")
            new_state = (ck, cv, pos, tok, active, stop, keys, dl)
            return new_state, (first, done)

        fn = telemetry.instrument_jit(
            jax.jit(admit, donate_argnums=(4, 5)), "serve.admit",
            key=(self.telemetry_label, self.S, A, P),
            fields={"server": self.telemetry_label, "pool": self.S,
                    "a_bucket": A, "p_bucket": P,
                    # the A-lane prefill cache pair — the admit
                    # program's transient scratch the budget check
                    # prices (pool_state_bytes(eng, A))
                    "cache_bytes": peng.cache_bytes()})
        self._admits[key2] = fn
        return fn
