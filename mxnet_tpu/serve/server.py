"""Continuous-batching decode server.

``DecodeServer`` turns the one-shot ``kv_generate`` decode stack into a
request-serving loop: callers ``submit()`` ragged requests at any time
and new sequences JOIN THE RUNNING COMPILED STEP at step boundaries
instead of waiting for a static batch to drain (the Orca / vLLM
continuous-batching design, rebuilt on this repo's trace discipline).

Scheduler shape (one ``pump()`` = one step boundary):

1. **admit** — while a slot is free and a request is pending, dispatch
   the per-bucket admission executable (prefill + first token into the
   slot's cache columns).  Pool sizes are pinned to the
   ``MXNET_SERVE_POOL_SIZES`` set; when the backlog outgrows the pool
   the state is padded up to the next pinned size (a handful of
   retraces per server lifetime, never per request).
2. **step** — if any slot is live, dispatch ONE decode-step executable
   (``serve.engine.PoolPrograms.step_fn``): every active slot advances
   one token, retired slots are masked.  The dispatch is async — the
   host never blocks here.
3. **drain** — read back the PREVIOUS dispatches' small
   ``(token, emitted, done)`` arrays (they are ready or nearly ready
   while the device runs the just-dispatched step), route tokens to the
   per-request ``TokenStream``s, free retired slots.  This is the ONE
   host readback per step, batched and off the hot path: the device
   queue already holds the next step when the host touches data.

EOS (``eos_id``) and per-request ``max_new_tokens`` retirement are
computed ON DEVICE by the step itself; the host only learns about them
in drain.  Backpressure: ``submit`` blocks (or raises with
``nowait=True``) once ``max_pending`` requests are queued.

``MXNET_SERVE_SYNC=1`` — or a model the slot-pool gate rejects — serves
each request through one ``kv_generate`` call instead (no continuous
batching, same token streams); the server API is unchanged.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as onp

from ..base import MXNetError

__all__ = ["DecodeServer", "TokenStream", "serve_counters",
           "reset_serve_counters"]

# process-wide AGGREGATE dispatch accounting — every DecodeServer in
# the process increments it, so with several servers the numbers
# interleave.  Per-server truth lives in ``DecodeServer.counters``
# (tests/test_serve.py pins 1 step dispatch per decode step at steady
# state against it; benchmark/serve_bench.py reports it).
serve_counters = {"step_dispatches": 0, "admit_dispatches": 0,
                  "sync_requests": 0, "pool_grows": 0}


def reset_serve_counters():
    for k in serve_counters:
        serve_counters[k] = 0


def _pool_sizes_from_env():
    raw = os.environ.get("MXNET_SERVE_POOL_SIZES", "1,2,4,8")
    try:
        sizes = sorted({int(x) for x in raw.split(",") if x.strip()})
    except ValueError:
        raise MXNetError(f"MXNET_SERVE_POOL_SIZES={raw!r}: expected a "
                         "comma-separated list of slot counts")
    if not sizes or sizes[0] < 1:
        raise MXNetError(f"MXNET_SERVE_POOL_SIZES={raw!r}: slot counts "
                         "must be positive")
    return tuple(sizes)


def _next_pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


class TokenStream:
    """Streaming view of one request's continuation.

    Iterate it for token ids as they decode (blocking; ends at
    retirement), or call :meth:`tokens` to wait for completion.  Every
    iteration replays from the first token, so a finished stream can be
    re-iterated and concurrent consumers each see the full stream.
    Each token's host-arrival wall time is kept in :attr:`times` (the
    latency source for ``benchmark/serve_bench.py``).  ``detokenize``
    (a ``token_id -> str`` callable) enables :meth:`text` /
    :meth:`text_iter` streaming detokenization."""

    def __init__(self, request_id, detokenize=None, on_token=None):
        self.request_id = request_id
        self.submit_time = time.perf_counter()
        self.times = []
        self._detok = detokenize
        self._on_token = on_token
        self._cv = threading.Condition()
        self._toks = []
        self._done = threading.Event()
        self._error = None

    # -- producer side (server loop) ------------------------------------ #
    def _push(self, tok):
        self.times.append(time.perf_counter())
        with self._cv:
            self._toks.append(tok)
            self._cv.notify_all()
        if self._on_token is not None:
            try:
                self._on_token(self.request_id, tok)
            except Exception as e:
                # a buggy per-request callback fails ITS stream only —
                # the scheduler thread (and every other client's
                # stream) must survive it
                self._on_token = None
                self._finish(e)

    def _finish(self, error=None):
        with self._cv:
            if self._error is None:   # first error wins (a callback
                self._error = error   # failure isn't erased by the
            self._done.set()          # slot's later clean retirement)
            self._cv.notify_all()

    # -- consumer side --------------------------------------------------- #
    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while i >= len(self._toks) and not self._done.is_set():
                    self._cv.wait()
                if i >= len(self._toks):
                    if self._error is not None:
                        raise self._error
                    return
                tok = self._toks[i]
            yield tok
            i += 1

    @property
    def done(self):
        return self._done.is_set()

    def tokens(self, timeout=None):
        """Block until the request retires; return the full token list."""
        if not self._done.wait(timeout):
            raise MXNetError(f"request {self.request_id} not finished "
                             f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return list(self._toks)

    def text_iter(self):
        """Streaming detokenization: yield text piece per token."""
        if self._detok is None:
            raise MXNetError("TokenStream has no detokenize callable")
        for tok in self:
            yield self._detok(tok)

    def text(self, timeout=None):
        if self._detok is None:
            raise MXNetError("TokenStream has no detokenize callable")
        return "".join(self._detok(t) for t in self.tokens(timeout))


class _Request:
    __slots__ = ("prompt", "max_new", "seed", "stream")

    def __init__(self, prompt, max_new, seed, stream):
        self.prompt = prompt
        self.max_new = max_new
        self.seed = seed
        self.stream = stream


class DecodeServer:
    """Continuous-batching decode server over a slot-pool KV cache.

    ``submit()`` never waits for other requests: a free slot is filled
    at the next step boundary and the request's tokens stream out as
    they decode.  ``temperature``/``top_k``/``eos_id`` are server-level
    (they shape the compiled sampler); ``seed`` is per-request — a
    served stream reproduces ``kv_generate(model, prompt[None],
    max_new_tokens, temperature, top_k, seed)`` token-for-token.

    ``autostart=True`` runs the scheduler on a background thread.  With
    ``autostart=False`` the owner calls :meth:`pump` — one admission +
    step + drain round per call — which the scheduler tests and the
    benchmark use to drive the loop deterministically.
    """

    def __init__(self, model, *, max_total_len=None, pool_sizes=None,
                 temperature=0.0, top_k=0, eos_id=None,
                 weights="native", max_pending=256, detokenize=None,
                 autostart=True):
        from .engine import PoolPrograms, pool_state_init

        self.model = model
        self.T = int(max_total_len if max_total_len is not None
                     else model._cfg.max_length)
        self.pool_sizes = tuple(pool_sizes) if pool_sizes is not None \
            else _pool_sizes_from_env()
        if not self.pool_sizes \
                or list(self.pool_sizes) != sorted(set(self.pool_sizes)) \
                or self.pool_sizes[0] < 1:
            raise MXNetError(f"pool_sizes {self.pool_sizes} must be "
                             "strictly increasing positive slot counts")
        self.temperature, self.top_k = temperature, top_k
        self.eos_id = eos_id
        self.weights = weights
        self.max_pending = int(max_pending)
        self._detok = detokenize

        self.sync_mode = os.environ.get("MXNET_SERVE_SYNC", "0") == "1"
        self.sync_reason = "MXNET_SERVE_SYNC=1" if self.sync_mode \
            else None
        self._progs = None
        if not self.sync_mode:
            try:
                self._progs = PoolPrograms(
                    model, self.pool_sizes[0], self.T, temperature,
                    top_k, eos_id, weights)
            except MXNetError as e:
                # models the slot-pool gate rejects still serve, one
                # request at a time, through the kv_generate fallback
                self.sync_mode = True
                self.sync_reason = str(e)
        self._state = None if self.sync_mode \
            else pool_state_init(self._progs.eng)

        # scheduler bookkeeping (single scheduler thread; submit() is
        # the only cross-thread writer and it only touches _pending)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending = deque()
        self._stopping = False
        self._slots = [None] * self.pool_sizes[0]   # slot -> _Request
        self._inflight = deque()   # (kind, arrays, slot_snapshot/req)
        self._next_id = 0
        self._steps = 0
        self._occupied_lane_steps = 0
        self._capacity_lane_steps = 0   # sums len(_slots) per step, so
        # occupancy stays honest across pool growth (S changes mid-run)
        # per-server dispatch accounting (the module-level
        # serve_counters aggregate is also incremented)
        self.counters = {"step_dispatches": 0, "admit_dispatches": 0,
                         "sync_requests": 0, "pool_grows": 0}
        self._thread = None
        if autostart:
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-serve", daemon=True)
            self._thread.start()

    # -- public API ------------------------------------------------------ #
    def submit(self, prompt_tokens, max_new_tokens=32, seed=0,
               nowait=False, on_token=None):
        """Queue one request; returns its :class:`TokenStream`.

        Blocks while ``max_pending`` requests are already queued
        (``nowait=True`` raises instead — pool-full backpressure is a
        visible error, not an unbounded queue)."""
        prompt = onp.asarray(
            prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
            else prompt_tokens, dtype=onp.int32).reshape(-1)
        if prompt.size == 0:
            raise MXNetError("empty prompt")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.T:
            raise MXNetError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the pool cache length "
                f"{self.T}")
        seed = int(seed)
        if not -2 ** 31 <= seed < 2 ** 31:
            # the slot pool carries the seed as a traced int32 operand;
            # rejecting it HERE keeps an oversized seed a caller error
            # instead of an OverflowError on the scheduler thread
            raise MXNetError(
                f"seed {seed} does not fit int32 — fold larger seeds "
                "on the host before submitting")
        with self._work:
            if self._stopping:
                raise MXNetError("server is closed")
            while len(self._pending) >= self.max_pending:
                if nowait:
                    raise MXNetError(
                        f"backpressure: {len(self._pending)} requests "
                        f"pending (max_pending={self.max_pending})")
                if self._thread is None:
                    # no scheduler thread to drain the queue — blocking
                    # here would deadlock the pump()-driving thread
                    raise MXNetError(
                        f"backpressure: {len(self._pending)} requests "
                        f"pending (max_pending={self.max_pending}) and "
                        "no scheduler thread (autostart=False) — call "
                        "pump() to drain, or submit(nowait=True)")
                self._work.wait(0.05)
                if self._stopping:
                    raise MXNetError("server is closed")
            stream = TokenStream(self._next_id, self._detok, on_token)
            self._next_id += 1
            self._pending.append(
                _Request(prompt, int(max_new_tokens), int(seed),
                         stream))
            self._work.notify_all()
        return stream

    def _count(self, key):
        self.counters[key] += 1
        serve_counters[key] += 1

    def reset_counters(self):
        for k in self.counters:
            self.counters[k] = 0

    def stats(self):
        """Scheduler/occupancy counters for benchmarks."""
        S = len(self._slots)
        return {
            "num_slots": S,
            "steps": self._steps,
            "occupancy": (self._occupied_lane_steps /
                          self._capacity_lane_steps
                          if self._capacity_lane_steps else 0.0),
            "pending": len(self._pending),
            "in_flight": sum(r is not None for r in self._slots),
            "sync_mode": self.sync_mode,
        }

    def close(self, drain=True, timeout=60.0):
        """Stop the scheduler.  ``drain=True`` serves everything already
        submitted first; otherwise queued/in-flight requests fail with
        a server-closed error."""
        deadline = time.time() + timeout
        if drain:
            while (self._pending or
                   any(r is not None for r in self._slots) or
                   self._inflight):
                if self._thread is None or not self._thread.is_alive():
                    # no scheduler left to drain the backlog — either
                    # autostart=False, or a PRIOR close() timed out and
                    # the thread has since exited at its _stopping
                    # check with work outstanding; pump from here so
                    # "call close() again" actually finishes the drain
                    if not self.pump():
                        break
                elif time.time() > deadline:
                    raise MXNetError("close(drain=True) timed out")
                else:
                    time.sleep(0.002)
        with self._work:
            self._stopping = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=max(deadline - time.time(), 0.1))
            if self._thread.is_alive():
                # the scheduler is mid-pump (e.g. a pool-growth retrace
                # compiling) and owns _slots/_inflight — tearing them
                # down under it would double-route token waves.  It
                # exits at its next _stopping check; call close() again
                # to finish teardown.
                raise MXNetError(
                    "close() timed out waiting for the scheduler "
                    "thread (still inside a dispatch/retrace); it "
                    "stops at the next step boundary — call close() "
                    "again to finish teardown")
        self._flush_drain(final=True)
        self._teardown(MXNetError("server closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc == (None, None, None))

    # -- scheduler ------------------------------------------------------- #
    def pump(self):
        """One scheduler round: admissions, one step dispatch, drain.
        Returns True if any work happened (False = fully idle: nothing
        pending, nothing in flight — the loop thread sleeps on that)."""
        if self.sync_mode:
            return self._pump_sync()
        worked = self._admit_pending()
        stepped = False
        if any(r is not None for r in self._slots):
            self._dispatch_step()
            worked = stepped = True
        # drain PREVIOUS dispatches' readbacks: while stepping, the
        # newest dispatch stays in flight so the device computes it
        # while the host routes the older (S,)-sized arrays; once the
        # loop stops stepping, everything drains so streams finish
        worked |= self._flush_drain(keep=1 if stepped else 0)
        return worked

    def _loop(self):
        while True:
            with self._work:
                if self._stopping:
                    return
            try:
                worked = self.pump()
            except Exception as e:
                # a runtime dispatch failure (device OOM, XLA error, a
                # growth retrace) must not silently kill the scheduler
                # thread and hang every consumer: fail all outstanding
                # streams with the error and stop serving
                self._fail_all(e)
                return
            if not worked:
                with self._work:
                    if self._stopping:
                        return
                    if not self._pending and not self._inflight:
                        self._work.wait(0.05)

    def _fail_all(self, exc):
        err = exc if isinstance(exc, MXNetError) else \
            MXNetError(f"serving loop failed: {exc!r}")
        with self._work:
            self._stopping = True
            self._work.notify_all()
        self._inflight.clear()   # readbacks are dropped, not routed
        self._teardown(err)

    def _teardown(self, err):
        """Fail every queued and in-flight request with ``err``.  The
        snapshot-and-clear runs under the lock; streams are finished
        OUTSIDE it — _finish wakes consumer threads (and on_token
        callers) that may immediately re-enter submit()/stats()."""
        with self._lock:
            dropped = list(self._pending)
            self._pending.clear()
            leftover = [r for r in self._slots if r is not None]
            self._slots = [None] * len(self._slots)
            self._work.notify_all()
        for req in dropped + leftover:
            req.stream._finish(err)

    # admissions --------------------------------------------------------- #
    def _take_pending(self):
        with self._lock:
            if not self._pending:
                return None
            req = self._pending.popleft()
            self._work.notify_all()
            return req

    def _free_slot(self):
        for i, r in enumerate(self._slots):
            if r is None:
                return i
        return None

    def _maybe_grow(self):
        """Grow the pool to the next pinned size when the backlog wants
        more lanes than exist (retrace happens at most
        ``len(pool_sizes) - 1`` times, never per request)."""
        from .engine import PoolPrograms, pool_state_grow

        S = len(self._slots)
        busy = sum(r is not None for r in self._slots)
        want = busy + len(self._pending)
        bigger = [s for s in self.pool_sizes if s > S]
        if not bigger or want <= S:
            return
        new_s = S
        for s in bigger:
            new_s = s
            if s >= want:
                break
        progs = PoolPrograms(self.model, new_s, self.T,
                             self.temperature, self.top_k, self.eos_id,
                             self.weights)
        # the old pool's in-flight readbacks refer to old slot indices;
        # they stay valid — slots only ever grow
        self._progs = progs
        self._state = pool_state_grow(self._state, new_s)
        with self._lock:
            self._slots.extend([None] * (new_s - S))
        self._count("pool_grows")

    def _admit_pending(self):
        admitted = may_retire = False
        self._maybe_grow()
        while True:
            slot = self._free_slot()
            if slot is None:
                break
            # pop + record into the slot table ATOMICALLY: a request
            # must never be invisible to close(drain=True)'s "anything
            # outstanding?" predicate (or to _fail_all) while its
            # admission dispatch is still being built
            with self._lock:
                if not self._pending:
                    break
                req = self._pending.popleft()
                self._slots[slot] = req
                self._work.notify_all()
            self._dispatch_admit(req, slot)
            admitted = True
            may_retire |= req.max_new == 1
        if may_retire:
            # a 1-token budget retires INSIDE the admission executable;
            # read the (first_tok, done) flags back now so its slot
            # frees before the step-dispatch decision — no wasted
            # dispatch.  Every other admission drains lazily with the
            # step readbacks, off the hot path (an EOS on the very
            # first token costs at most one masked-lane step).
            self._drain_admits()
        return admitted

    def _dispatch_admit(self, req, slot):
        P = req.prompt.size
        bucket = min(_next_pow2(max(P, 8)), self.T)
        fn = self._progs.admit_fn(bucket)
        padded = onp.zeros((1, bucket), onp.int32)
        padded[0, :P] = req.prompt
        meta = onp.array([P, slot, P + req.max_new - 1, req.seed],
                         onp.int32)
        param_vals, q8, sw = self._progs.operands
        new_state, (first, done) = fn(param_vals, padded, meta,
                                      *self._state)
        self._state = new_state
        self._count("admit_dispatches")
        self._inflight.append(("admit", (first, done), (slot, req)))

    # the step ------------------------------------------------------------ #
    def _dispatch_step(self):
        param_vals, q8, sw = self._progs.operands
        new_state, out = self._progs.step_fn()(
            param_vals, q8, sw, *self._state)
        self._state = new_state
        self._count("step_dispatches")
        self._steps += 1
        self._occupied_lane_steps += sum(
            r is not None for r in self._slots)
        self._capacity_lane_steps += len(self._slots)
        self._inflight.append(("step", out, list(self._slots)))

    # drain ---------------------------------------------------------------- #
    def _drain_admits(self):
        """Route every in-flight ADMIT readback (selective drain is
        stream-order-safe: an admit is always a request's first entry,
        and step entries only touch other, older requests)."""
        rest = deque()
        while self._inflight:
            kind, arrays, meta = self._inflight.popleft()
            if kind != "admit":
                rest.append((kind, arrays, meta))
                continue
            self._route_admit(arrays, meta)
        self._inflight = rest

    def _route_admit(self, arrays, meta):
        slot, req = meta
        first = int(onp.asarray(arrays[0]))
        done = bool(onp.asarray(arrays[1]))
        req.stream._push(first)
        if done:
            req.stream._finish()
            with self._lock:
                self._slots[slot] = None

    def _flush_drain(self, keep=0, final=False):
        """Route in-flight dispatches' readback arrays to their streams
        and free retired slots, oldest-first (the device stream is
        FIFO, so only the newest entries can still be computing).
        ``keep`` leaves that many newest entries in flight — the
        host/device overlap while the loop is actively stepping."""
        if final:
            keep = 0
        worked = False
        while len(self._inflight) > keep:
            kind, arrays, meta = self._inflight.popleft()
            worked = True
            if kind == "admit":
                self._route_admit(arrays, meta)
            else:
                toks, emitted, done = (onp.asarray(a) for a in arrays)
                snapshot = meta
                for slot, req in enumerate(snapshot):
                    if req is None or not emitted[slot]:
                        continue
                    req.stream._push(int(toks[slot]))
                    if done[slot]:
                        req.stream._finish()
                        with self._lock:
                            if self._slots[slot] is req:
                                self._slots[slot] = None
        return worked

    # sync fallback -------------------------------------------------------- #
    def _pump_sync(self):
        from ..models.decoding import kv_generate

        req = self._take_pending()
        if req is None:
            return False
        self._count("sync_requests")
        try:
            out = kv_generate(self.model, req.prompt[None],
                              max_new_tokens=req.max_new,
                              temperature=self.temperature,
                              top_k=self.top_k, seed=req.seed,
                              weights=self.weights)
            new = out[0, req.prompt.size:]
            if self.eos_id is not None:
                for t in new:
                    req.stream._push(int(t))
                    if int(t) == self.eos_id:
                        break
                req.stream._finish()
            else:
                for t in new:
                    req.stream._push(int(t))
                req.stream._finish()
        except Exception as e:                 # surface, don't hang
            req.stream._finish(e)
        return True
