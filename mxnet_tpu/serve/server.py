"""Continuous-batching decode server.

``DecodeServer`` turns the one-shot ``kv_generate`` decode stack into a
request-serving loop: callers ``submit()`` ragged requests at any time
and new sequences JOIN THE RUNNING COMPILED STEP at step boundaries
instead of waiting for a static batch to drain (the Orca / vLLM
continuous-batching design, rebuilt on this repo's trace discipline).

Scheduler shape (one ``pump()`` = one step boundary):

1. **admit** — gather EVERY currently pending request the free slots
   can take into one wave and dispatch ONE bucketed ``(A, P)``
   admission executable for it (batched prefill + first tokens into
   all the admitted slots' cache columns): a burst of k arrivals at a
   step boundary costs 1 admit dispatch, not k.  Wave/bucket sizes are
   pinned to the ``MXNET_SERVE_ADMIT_SIZES`` /
   ``MXNET_SERVE_PREFILL_BUCKETS`` ladders (defaults derived from the
   pool sizes / cache length), so compile count is bounded by the
   ladder product; a wave larger than the biggest ``A`` bucket spills
   to a second dispatch in the same pump.  Pool sizes are pinned to
   the ``MXNET_SERVE_POOL_SIZES`` set; when the backlog outgrows the
   pool the state is padded up to the next pinned size (a handful of
   retraces per server lifetime, never per request).
2. **step** — if any slot is live, dispatch ONE decode-step executable
   (``serve.engine.PoolPrograms.step_fn``): every active slot advances
   one token, retired slots are masked.  The dispatch is async — the
   host never blocks here.
3. **drain** — read back the PREVIOUS dispatches' small
   ``(token, emitted, done)`` arrays (they are ready or nearly ready
   while the device runs the just-dispatched step), route tokens to the
   per-request ``TokenStream``s, free retired slots.  This is the ONE
   host readback per step, batched and off the hot path: the device
   queue already holds the next step when the host touches data.

EOS (``eos_id``) and per-request ``max_new_tokens`` retirement are
computed ON DEVICE by the step itself; the host only learns about them
in drain.  Backpressure: ``submit`` blocks (or raises with
``nowait=True``) once ``max_pending`` requests are queued.

``MXNET_SERVE_SYNC=1`` — or a model the slot-pool gate rejects — serves
each request through one ``kv_generate`` call instead (no continuous
batching, same token streams); the server API is unchanged.

Memory (ISSUE 10): the resident pool is registered with the process-
wide ``telemetry.memory.ACCOUNTANT`` (``device_bytes{subsystem=
"serve.kv_pool"}``), and ``MXNET_SERVE_HBM_BUDGET`` /
``DecodeServer(hbm_budget=)`` bounds the server's device-resident
serving state: an over-budget pool growth or admission-scratch
allocation raises a clean ``MXNetError`` naming requested vs available
bytes instead of an allocator OOM.  ``stats()`` reports
``pool_bytes`` next to occupancy.

Paged KV (ISSUE 16): the resident pool is PAGED — each sequence holds
only the fixed-size pages (``MXNET_SERVE_PAGE_SIZE`` tokens each) its
cached positions occupy, mapped through per-slot page tables passed as
traced operands (allocation churn never retraces).  Identical prompt
prefixes SHARE pages copy-on-write (``MXNET_SERVE_PREFIX_CACHE``): a
full prefix hit admits with ZERO prefill dispatches and a TTFT of one
decode step.  Prompts past the largest pinned prefill bucket stream in
over several CHUNKED-PREFILL dispatches instead of being rejected —
the only hard length limit is the pool cache length (docs/SERVING.md).

Fault tolerance (ISSUE 13): ``submit(deadline=)`` /
``MXNET_SERVE_DEADLINE`` give every request a wall-clock budget the
STEP EXECUTABLE enforces (a per-slot deadline rides the slot-state
vector next to the sampling keys; the step takes a ``now`` operand and
folds expiry into the same device-side ``done`` mask as EOS — zero
extra dispatches).  ``TokenStream.cancel()`` frees the slot at the
next step boundary without touching co-resident lanes.  A scheduler
watchdog fails every in-flight stream with the underlying error when
the pump thread dies or a dispatch wedges past
``MXNET_SERVE_STEP_TIMEOUT`` — no consumer ever blocks forever — and
pump/admit/step/verify are ``MXNET_FAULT_INJECT`` sites so all of it
is exercised deterministically in tier-1 (docs/SERVING.md).

Speculative decoding (ISSUE 17): on greedy servers a cheap host-side
drafter (``serve.draft.NGramDrafter`` by default; any
``serve.draft.Drafter`` plugs in) proposes up to
``MXNET_SERVE_SPEC_DEPTH`` continuation tokens per slot between
steps, and ONE bucketed ``(S, k)`` verify dispatch
(``PoolPrograms.verify_fn``, k pinned to the ``MXNET_SERVE_SPEC_SIZES``
ladder) scores every proposal and accepts each slot's longest
matching prefix device-side — several tokens per dispatch when the
drafts land, exactly one (the plain-step guarantee) when they don't.
Greedy streams stay token-for-token identical to ``kv_generate``;
sampled pools never draft (acceptance compares argmax tokens, exact
only at temperature 0).  ``MXNET_SERVE_SPEC=0`` is the escape hatch.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from collections.abc import MutableMapping

import numpy as onp

from .. import telemetry
from ..base import MXNetError
from ..telemetry.faults import fault_point
from . import schema

__all__ = ["DecodeServer", "TokenStream", "serve_counters",
           "reset_serve_counters"]

# process-wide AGGREGATE dispatch accounting — every DecodeServer in
# the process increments it, so with several servers the numbers
# interleave.  Per-server truth lives in ``DecodeServer.counters``
# (tests/test_serve.py pins 1 step dispatch per decode step at steady
# state against it; benchmark/serve_bench.py reports it).  Mutations go
# through ``_bump`` / ``reset_serve_counters`` — both take
# ``_counters_lock``, so a reset racing a live scheduler thread's
# increments can't lose counts (read-modify-write vs. reassign).
serve_counters = {"step_dispatches": 0, "admit_dispatches": 0,
                  "sync_requests": 0, "pool_grows": 0,
                  "prefix_hits": 0, "cow_copies": 0,
                  "chunk_dispatches": 0, "verify_dispatches": 0,
                  "draft_proposed": 0, "draft_accepted": 0,
                  "draft_rejected": 0}
_counters_lock = threading.Lock()
_server_seq = itertools.count()


def _bump(key, n=1):
    with _counters_lock:
        serve_counters[key] += n


def reset_serve_counters():
    with _counters_lock:
        for k in serve_counters:
            serve_counters[k] = 0


class _CounterView(MutableMapping):
    """The historical ``DecodeServer.counters`` dict API as a live view
    over per-server registry counters (``serve_<key>_total{server=}``),
    so benchmarks/tests keep reading ``srv.counters["step_dispatches"]``
    while exporters see the same numbers in ``telemetry.snapshot()`` /
    ``render_prometheus()``.  Assignment (the reset path) writes the
    backing counter; iteration order is the historical key order."""

    _KEYS = ("step_dispatches", "admit_dispatches", "sync_requests",
             "pool_grows", "prefix_hits", "cow_copies",
             "chunk_dispatches", "verify_dispatches",
             "draft_proposed", "draft_accepted", "draft_rejected")

    def __init__(self, server_label):
        self._c = {k: telemetry.counter(f"serve_{k}_total",
                                        server=server_label)
                   for k in self._KEYS}

    def inc(self, key, n=1):
        self._c[key].inc(n)

    def __getitem__(self, key):
        return self._c[key].value

    def __setitem__(self, key, value):
        self._c[key]._assign(int(value))

    def __delitem__(self, key):
        raise MXNetError("DecodeServer.counters keys are fixed")

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def __repr__(self):
        return repr(dict(self))


def _parse_sizes(var, raw, what):
    try:
        sizes = sorted({int(x) for x in raw.split(",") if x.strip()})
    except ValueError:
        raise MXNetError(f"{var}={raw!r}: expected a "
                         f"comma-separated list of {what}")
    if not sizes or sizes[0] < 1:
        raise MXNetError(f"{var}={raw!r}: {what} must be positive")
    return tuple(sizes)


def _pool_sizes_from_env():
    return _parse_sizes("MXNET_SERVE_POOL_SIZES",
                        os.environ.get("MXNET_SERVE_POOL_SIZES",
                                       "1,2,4,8"), "slot counts")


def _hbm_budget_from_env():
    """``MXNET_SERVE_HBM_BUDGET``: bytes (K/M/G suffixes accepted) the
    server's device-resident serving state may occupy; unset = no
    limit."""
    from ..telemetry.memory import parse_bytes

    raw = os.environ.get("MXNET_SERVE_HBM_BUDGET")
    if raw is None:
        return None
    return parse_bytes(raw, "MXNET_SERVE_HBM_BUDGET")


def _page_size_from_env():
    """``MXNET_SERVE_PAGE_SIZE``: tokens per KV page (the paged-pool
    allocation granule); default 16."""
    raw = os.environ.get("MXNET_SERVE_PAGE_SIZE", "16")
    try:
        page = int(raw)
    except ValueError:
        raise MXNetError(f"MXNET_SERVE_PAGE_SIZE={raw!r}: expected a "
                         "positive integer token count")
    if page < 1:
        raise MXNetError(f"MXNET_SERVE_PAGE_SIZE={raw!r}: page size "
                         "must be >= 1 tokens")
    return page


def _kv_dtype_from_env():
    """``MXNET_SERVE_KV_DTYPE``: storage dtype of the paged KV pool —
    ``int8`` stores pages as int8 codes with per-page-per-head float32
    scales (~4x smaller pages, lossy: PARITY.md pins the tolerance);
    default ``native`` keeps the model's cache dtype (lossless, the
    pre-int8 behavior).  ``DecodeServer(kv_dtype=)`` wins over the
    env."""
    raw = os.environ.get("MXNET_SERVE_KV_DTYPE", "native").lower()
    if raw in ("native", "f32", "float32", "bf16", "bfloat16", ""):
        return "native"
    if raw == "int8":
        return "int8"
    raise MXNetError(f"MXNET_SERVE_KV_DTYPE={raw!r}: expected 'native' "
                     "(model cache dtype) or 'int8'")


def _prefix_cache_from_env():
    """``MXNET_SERVE_PREFIX_CACHE``: 0 disables copy-on-write shared-
    prefix caching (default on)."""
    return os.environ.get("MXNET_SERVE_PREFIX_CACHE", "1") != "0"


def _spec_from_env():
    """``MXNET_SERVE_SPEC``: 0 disables speculative draft-and-verify
    decoding (default on; it only engages on greedy servers —
    sampled pools always run plain depth-1 steps)."""
    return os.environ.get("MXNET_SERVE_SPEC", "1") != "0"


def _spec_depth_from_env():
    """``MXNET_SERVE_SPEC_DEPTH``: max draft tokens proposed per slot
    per verify dispatch (default 4; 0 disables speculation, same as
    ``MXNET_SERVE_SPEC=0``)."""
    raw = os.environ.get("MXNET_SERVE_SPEC_DEPTH", "4")
    try:
        depth = int(raw)
    except ValueError:
        raise MXNetError(f"MXNET_SERVE_SPEC_DEPTH={raw!r}: expected "
                         "a non-negative integer draft depth")
    if depth < 0:
        raise MXNetError(f"MXNET_SERVE_SPEC_DEPTH={raw!r}: draft "
                         "depth must be >= 0")
    return depth


def _spec_sizes_from_env(depth):
    """``MXNET_SERVE_SPEC_SIZES``: the pinned k-bucket ladder for the
    verify executable — compile count is bounded by its length, the
    PR-8 admit-ladder discipline.  Default: powers of two up to the
    speculation depth."""
    raw = os.environ.get("MXNET_SERVE_SPEC_SIZES")
    if raw is None:
        return tuple(_pow2_ladder(1, max(depth, 1)))
    return _parse_sizes("MXNET_SERVE_SPEC_SIZES", raw, "draft depths")


def _parse_seconds(var, raw):
    """A positive float seconds knob; unset/0 = None, malformed = loud
    (the shared ``base.parse_seconds`` discipline)."""
    from ..base import parse_seconds

    val = parse_seconds(var, raw)
    return val if val is not None and val > 0 else None


def _default_deadline_from_env():
    """``MXNET_SERVE_DEADLINE``: default per-request wall-clock budget
    in seconds (submit(deadline=) wins); unset/0 = none."""
    return _parse_seconds("MXNET_SERVE_DEADLINE",
                          os.environ.get("MXNET_SERVE_DEADLINE"))


def _step_timeout_from_env():
    """``MXNET_SERVE_STEP_TIMEOUT``: seconds one scheduler pump
    (admission + step dispatch + drain) may run before the watchdog
    declares the dispatch wedged and fails all in-flight streams;
    unset/0 = disabled."""
    return _parse_seconds("MXNET_SERVE_STEP_TIMEOUT",
                          os.environ.get("MXNET_SERVE_STEP_TIMEOUT"))


def _pow2_ladder(start, top):
    """``start``, doubling, until ``top`` caps the ladder."""
    sizes, a = [], start
    while a < top:
        sizes.append(a)
        a *= 2
    sizes.append(top)
    return sizes


def _admit_sizes_default(pool_sizes):
    """Default admission-wave bucket ladder: powers of two up to the
    largest pinned pool size (a wave can never exceed the free slot
    count, so bigger buckets would only pad) — bounds a partially full
    wave's masked-row overcompute to < 2x while keeping single-request
    trickle admission at bucket 1."""
    return tuple(_pow2_ladder(1, max(pool_sizes)))


def _admit_sizes_from_env(pool_sizes):
    raw = os.environ.get("MXNET_SERVE_ADMIT_SIZES")
    if raw is None:
        return _admit_sizes_default(pool_sizes)
    return _parse_sizes("MXNET_SERVE_ADMIT_SIZES", raw, "wave sizes")


def _prefill_buckets_default(T):
    """Default prompt-length bucket ladder: powers of two from 8 up to
    the cache length ``T`` (each clamped to ``T``) — the same shape the
    per-request admission used, now pinned so compile count stays
    bounded by the ladder product."""
    return tuple(sorted({min(b, T) for b in _pow2_ladder(8, T)}))


def _prefill_buckets_from_env(T):
    raw = os.environ.get("MXNET_SERVE_PREFILL_BUCKETS")
    if raw is None:
        return _prefill_buckets_default(T)
    buckets = _parse_sizes("MXNET_SERVE_PREFILL_BUCKETS", raw,
                           "prompt bucket lengths")
    return tuple(sorted({min(b, T) for b in buckets}))


def _bucket_for(ladder, n):
    """Smallest ladder entry >= n (the caller guarantees one exists)."""
    for b in ladder:
        if b >= n:
            return b
    raise MXNetError(f"{n} exceeds the largest bucket {ladder[-1]}")


class _PrefixIndex:
    """Host-side copy-on-write shared-prefix page cache: a chained trie
    over FULL pages of prompt tokens, each node mapping one
    ``(parent, page-of-token-bytes)`` chunk to the pool page holding
    its K/V.  ``register`` pins a producer's prompt pages with one
    index-owned refcount each (so they outlive the producer's
    retirement); ``match`` walks the longest cached chain for a new
    prompt, and the admission path maps those pages READ-ONLY into the
    consumer's table row — zero prefill dispatches on a full hit.
    ``evict`` drops least-recently-touched LEAF nodes when the
    allocator runs dry, so the cache is exactly the pages nothing else
    wants yet.  Scheduler-thread-only, like the ``PagePool`` under
    it."""

    def __init__(self, page_size, pool):
        self.page = int(page_size)
        self.pool = pool
        self._nodes = {}    # (parent_id, chunk_bytes) -> node dict
        self._by_id = {}    # node id -> node (parent chains, eviction)
        self._ids = itertools.count(1)
        self._tick = itertools.count(1)

    def __len__(self):
        return len(self._nodes)

    def _chunk_key(self, prompt, parent, c):
        return (parent,
                prompt[c * self.page:(c + 1) * self.page].tobytes())

    def match(self, prompt):
        """Longest chain of cached FULL pages covering a prefix of
        ``prompt``: ``(num_matched_pages, [pool page ids])``."""
        pages, parent = [], 0
        for c in range(prompt.size // self.page):
            node = self._nodes.get(self._chunk_key(prompt, parent, c))
            if node is None:
                break
            node["last"] = next(self._tick)
            pages.append(node["page"])
            parent = node["id"]
        return len(pages), pages

    def register(self, prompt, length, slot_pages):
        """Index ``prompt[:length]``'s full pages, backed by the
        producer slot's ``slot_pages`` row.  Only NEWLY created nodes
        incref their page (existing nodes already own theirs); pages
        past the last FULL page are never indexed — their K/V columns
        get overwritten by the producer's own decode steps."""
        parent = 0
        for c in range(min(length // self.page, len(slot_pages))):
            key = self._chunk_key(prompt, parent, c)
            node = self._nodes.get(key)
            if node is None:
                node = {"id": next(self._ids), "key": key,
                        "page": slot_pages[c], "parent": parent,
                        "children": 0, "last": next(self._tick)}
                self._nodes[key] = node
                self._by_id[node["id"]] = node
                if parent:
                    self._by_id[parent]["children"] += 1
                self.pool.incref(node["page"])
            else:
                node["last"] = next(self._tick)
            parent = node["id"]

    def evict(self, need, protect=()):
        """Drop LRU leaf nodes (never pages in ``protect``) until
        ``need`` pool pages have actually come free — a decref only
        frees a page once no slot still maps it.  Returns pages
        freed."""
        protect = set(protect)
        before = self.pool.free_pages
        while self.pool.free_pages - before < need:
            leaves = [nd for nd in self._nodes.values()
                      if nd["children"] == 0
                      and nd["page"] not in protect]
            if not leaves:
                break
            self._drop(min(leaves, key=lambda nd: nd["last"]))
        return self.pool.free_pages - before

    def _drop(self, node):
        del self._nodes[node["key"]]
        del self._by_id[node["id"]]
        if node["parent"]:
            self._by_id[node["parent"]]["children"] -= 1
        self.pool.decref(node["page"])

    def drop_all(self):
        """Release every index-owned page ref (server teardown)."""
        for node in self._by_id.values():
            self.pool.decref(node["page"])
        self._nodes.clear()
        self._by_id.clear()


class TokenStream:
    """Streaming view of one request's continuation.

    Iterate it for token ids as they decode (blocking; ends at
    retirement), or call :meth:`tokens` to wait for completion.  Every
    iteration replays from the first token, so a finished stream can be
    re-iterated and concurrent consumers each see the full stream.
    Each token's host-arrival wall time is kept in :attr:`times` and
    the time-to-first-token (first arrival minus submit) separately in
    :attr:`ttft` — the latency sources for ``benchmark/serve_bench.py``
    (TTFT is the metric batched admission moves; inter-token gaps come
    from consecutive :attr:`times`).  ``detokenize`` (a ``token_id ->
    str`` callable) enables :meth:`text` / :meth:`text_iter` streaming
    detokenization."""

    def __init__(self, request_id, detokenize=None, on_token=None):
        self.request_id = request_id
        self.submit_time = time.perf_counter()
        self.times = []
        self._detok = detokenize
        self._on_token = on_token
        self._cv = threading.Condition()
        self._toks = []
        self._done = threading.Event()
        self._error = None
        self._cancel_hook = None   # wired by DecodeServer.submit
        self._cancelled = False
        # speculative-decoding ledger (scheduler-thread writes at
        # verify drains): draft tokens the verify dispatches accepted
        # into THIS stream vs proposed-but-rejected
        self.draft_accepted = 0
        self.draft_rejected = 0

    # -- producer side (server loop) ------------------------------------ #
    @property
    def ttft(self):
        """Time-to-first-token: first host arrival minus submit
        (``None`` until the first token lands) — the admission-latency
        metric, distinct from the inter-token gaps derivable from
        consecutive :attr:`times`."""
        return self.times[0] - self.submit_time if self.times else None

    def _push(self, tok):
        if self._done.is_set():
            # a late in-flight readback for a cancelled / deadline-
            # retired slot: the stream's token list is sealed
            return
        self.times.append(time.perf_counter())
        with self._cv:
            self._toks.append(tok)
            self._cv.notify_all()
        if self._on_token is not None:
            try:
                self._on_token(self.request_id, tok)
            except Exception as e:
                # a buggy per-request callback fails ITS stream only —
                # the scheduler thread (and every other client's
                # stream) must survive it
                self._on_token = None
                self._finish(e)

    def _finish(self, error=None):
        with self._cv:
            if self._error is None:   # first error wins (a callback
                self._error = error   # failure isn't erased by the
            self._done.set()          # slot's later clean retirement)
            self._cv.notify_all()

    # -- consumer side --------------------------------------------------- #
    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while i >= len(self._toks) and not self._done.is_set():
                    self._cv.wait()
                if i >= len(self._toks):
                    if self._error is not None:
                        raise self._error
                    return
                tok = self._toks[i]
            yield tok
            i += 1

    @property
    def done(self):
        return self._done.is_set()

    @property
    def cancelled(self):
        """True once :meth:`cancel` has taken effect (the stream is
        done with the tokens that arrived before cancellation)."""
        return self._cancelled

    @property
    def accept_rate(self):
        """Fraction of this request's proposed draft tokens the
        verify dispatches accepted (0.0 while nothing has been
        proposed; 1.0 means every draft matched the model's own
        greedy emission)."""
        total = self.draft_accepted + self.draft_rejected
        return self.draft_accepted / total if total else 0.0

    def cancel(self):
        """Cancel this request: a queued request is dropped
        immediately; an in-flight one has its pool slot freed at the
        NEXT STEP BOUNDARY by the scheduler — co-resident streams are
        untouched and no extra executable dispatch is spent (the lane
        is simply unmapped host-side, like any retired slot).  The
        stream finishes cleanly with the tokens received so far;
        idempotent, and a no-op once the request already retired.
        Returns True if the cancellation took effect."""
        hook = self._cancel_hook
        if hook is None:
            raise MXNetError(
                f"stream {self.request_id} is not cancellable "
                "(not attached to a server)")
        return hook()

    def tokens(self, timeout=None):
        """Block until the request retires; return the full token list.

        A timeout raises ``MXNetError`` but consumes nothing: the
        stream keeps filling, and the same consumer may call
        :meth:`tokens` (or iterate) again later and still drain the
        full stream."""
        if not self._done.wait(timeout):
            raise MXNetError(f"request {self.request_id} not finished "
                             f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return list(self._toks)

    def text_iter(self):
        """Streaming detokenization: yield text piece per token."""
        if self._detok is None:
            raise MXNetError("TokenStream has no detokenize callable")
        for tok in self:
            yield self._detok(tok)

    def text(self, timeout=None):
        if self._detok is None:
            raise MXNetError("TokenStream has no detokenize callable")
        return "".join(self._detok(t) for t in self.tokens(timeout))


class _Request:
    __slots__ = ("prompt", "max_new", "seed", "stream", "span",
                 "deadline", "cancelled", "retired")

    def __init__(self, prompt, max_new, seed, stream, deadline=None):
        self.prompt = prompt
        self.max_new = max_new
        self.seed = seed
        self.stream = stream
        # absolute wall-clock retirement budget on the server's
        # monotonic clock (None = no deadline); rides the slot-state
        # vector device-side once admitted
        self.deadline = deadline
        self.cancelled = False
        self.retired = False    # span closed (guards double-observe on
        # the cancel-vs-drain and teardown-after-failure races)
        # request-span telemetry, filled in at admission and emitted as
        # one ``serve_request`` event at retirement (docs/TELEMETRY.md)
        self.span = {}


class DecodeServer:
    """Continuous-batching decode server over a slot-pool KV cache.

    ``submit()`` never waits for other requests: a free slot is filled
    at the next step boundary and the request's tokens stream out as
    they decode.  ``temperature``/``top_k``/``eos_id`` are server-level
    (they shape the compiled sampler); ``seed`` is per-request — a
    served stream reproduces ``kv_generate(model, prompt[None],
    max_new_tokens, temperature, top_k, seed)`` token-for-token.

    ``autostart=True`` runs the scheduler on a background thread.  With
    ``autostart=False`` the owner calls :meth:`pump` — one admission +
    step + drain round per call — which the scheduler tests and the
    benchmark use to drive the loop deterministically.
    """

    def __init__(self, model, *, max_total_len=None, pool_sizes=None,
                 temperature=0.0, top_k=0, eos_id=None,
                 weights="native", max_pending=256, detokenize=None,
                 admit_sizes=None, prefill_buckets=None,
                 hbm_budget=None, default_deadline=None,
                 step_timeout=None, page_size=None, num_pages=None,
                 prefix_cache=None, spec=None, spec_depth=None,
                 spec_sizes=None, drafter=None, kv_dtype=None,
                 autostart=True):
        from ..telemetry.memory import parse_bytes
        from .draft import NGramDrafter
        from .engine import PagePool, PoolPrograms, pool_state_init

        self.model = model
        # fault-tolerance knobs (ISSUE 13): the server's monotonic
        # clock (monkeypatchable in tests for deterministic deadline
        # expiry) and its epoch — per-slot deadlines ride the state
        # vector as float32 seconds RELATIVE to the epoch, so float32
        # precision is spent on the server's lifetime, not on host
        # uptime
        self._clock = time.monotonic
        self._epoch = self._clock()
        self.default_deadline = default_deadline \
            if default_deadline is not None \
            else _default_deadline_from_env()
        if self.default_deadline is not None \
                and self.default_deadline <= 0:
            raise MXNetError("default_deadline must be positive seconds")
        self.step_timeout = step_timeout if step_timeout is not None \
            else _step_timeout_from_env()
        if self.step_timeout is not None and self.step_timeout <= 0:
            self.step_timeout = None   # 0 = wedge detection off, same
            # as the env path (a 0 budget would hair-trigger on every
            # in-progress pump at the watchdog's next poll)
        self._fatal = None          # the error the scheduler died with
        self._torn = False          # _teardown ran: the pool was
        # released and unaccounted — a wedged dispatch completing late
        # must not re-pin it (see _dispatch_step/_dispatch_admit)
        self._watchdog = None
        self._pump_t0 = None        # monotonic start of the loop's
        # current pump (None between pumps); read by the watchdog
        self.T = int(max_total_len if max_total_len is not None
                     else model._cfg.max_length)
        self.pool_sizes = tuple(pool_sizes) if pool_sizes is not None \
            else _pool_sizes_from_env()
        if not self.pool_sizes \
                or list(self.pool_sizes) != sorted(set(self.pool_sizes)) \
                or self.pool_sizes[0] < 1:
            raise MXNetError(f"pool_sizes {self.pool_sizes} must be "
                             "strictly increasing positive slot counts")
        # bucketed batched-admission ladders: wave sizes (A) and prompt
        # bucket lengths (P) — compile count per pool size is bounded
        # by len(admit_sizes) * len(prefill_buckets), lazily filled
        self.admit_sizes = tuple(admit_sizes) \
            if admit_sizes is not None \
            else _admit_sizes_from_env(self.pool_sizes)
        if not self.admit_sizes \
                or list(self.admit_sizes) != sorted(set(self.admit_sizes)) \
                or self.admit_sizes[0] < 1:
            raise MXNetError(f"admit_sizes {self.admit_sizes} must be "
                             "strictly increasing positive wave sizes")
        self.prefill_buckets = tuple(prefill_buckets) \
            if prefill_buckets is not None \
            else _prefill_buckets_from_env(self.T)
        if not self.prefill_buckets \
                or list(self.prefill_buckets) != \
                sorted(set(self.prefill_buckets)) \
                or self.prefill_buckets[0] < 1 \
                or self.prefill_buckets[-1] > self.T:
            raise MXNetError(
                f"prefill_buckets {self.prefill_buckets} must be "
                "strictly increasing positive prompt lengths within "
                f"the cache length {self.T}")
        self.temperature, self.top_k = temperature, top_k
        self.eos_id = eos_id
        self.weights = weights
        self.max_pending = int(max_pending)
        self._detok = detokenize
        # HBM budget (bytes) for this server's device-resident serving
        # state: the resident slot-pool KV cache plus admission prefill
        # scratch.  Growth/admission that would exceed it raises a
        # clean MXNetError naming the shortfall instead of letting the
        # allocator OOM mid-dispatch; None = unlimited.
        self.hbm_budget = parse_bytes(hbm_budget, "hbm_budget") \
            if hbm_budget is not None else _hbm_budget_from_env()
        # paged-KV knobs: page granule, total page count (None = the
        # dense-equivalent S * MAXP allotment, rescaled on pool
        # growth; an explicit count is pinned for the server's life)
        # and the COW shared-prefix cache switch
        self.page_size = int(page_size) if page_size is not None \
            else _page_size_from_env()
        if self.page_size < 1:
            raise MXNetError(f"page_size must be >= 1, "
                             f"got {self.page_size}")
        self._num_pages_fixed = num_pages is not None
        # paged-pool storage dtype (ISSUE 18): "int8" quantizes pages
        # at write time inside the same executables and halves-again
        # the per-page bytes vs bf16 (4x vs f32) — the equal-HBM
        # residency lever; "native" is the lossless default
        self.kv_dtype = str(kv_dtype).lower() if kv_dtype is not None \
            else _kv_dtype_from_env()
        if self.kv_dtype in ("f32", "float32", "bf16", "bfloat16"):
            self.kv_dtype = "native"
        if self.kv_dtype not in ("native", "int8"):
            raise MXNetError(f"kv_dtype must be 'native' or 'int8', "
                             f"got {kv_dtype!r}")
        self.prefix_cache_enabled = bool(prefix_cache) \
            if prefix_cache is not None else _prefix_cache_from_env()
        # speculative decoding knobs (ISSUE 17): draft-and-verify is
        # GREEDY-ONLY (acceptance compares argmax tokens — exact at
        # temperature 0, wrong otherwise), gated HERE so a sampled
        # server never builds a verify program.  Depth is clamped to
        # the largest pinned k bucket; a 0 depth disables speculation
        # like MXNET_SERVE_SPEC=0 does.
        self.spec_depth = int(spec_depth) if spec_depth is not None \
            else _spec_depth_from_env()
        if self.spec_depth < 0:
            raise MXNetError(f"spec_depth must be >= 0, "
                             f"got {self.spec_depth}")
        self.spec_sizes = tuple(spec_sizes) \
            if spec_sizes is not None \
            else _spec_sizes_from_env(self.spec_depth)
        if not self.spec_sizes \
                or list(self.spec_sizes) != sorted(set(self.spec_sizes)) \
                or self.spec_sizes[0] < 1:
            raise MXNetError(f"spec_sizes {self.spec_sizes} must be "
                             "strictly increasing positive draft "
                             "depths")
        self.spec_depth = min(self.spec_depth, self.spec_sizes[-1])
        self.spec_enabled = ((bool(spec) if spec is not None
                              else _spec_from_env())
                             and self.spec_depth > 0
                             and temperature == 0.0)
        self._drafter = drafter if drafter is not None \
            else NGramDrafter()
        # per-server telemetry identity: labels this server's registry
        # counters/histograms and its compile / serve_* events
        self.telemetry_label = f"srv{next(_server_seq)}"
        self._tele = {
            "ttft": telemetry.histogram("serve_ttft_seconds",
                                        server=self.telemetry_label),
            "gap": telemetry.histogram("serve_token_gap_seconds",
                                       server=self.telemetry_label),
            "wait": telemetry.histogram("serve_queue_wait_seconds",
                                        server=self.telemetry_label),
            "occ": telemetry.gauge("serve_occupancy",
                                   server=self.telemetry_label),
            "pages": telemetry.gauge("serve_pages_in_use",
                                     server=self.telemetry_label),
        }

        self.sync_mode = os.environ.get("MXNET_SERVE_SYNC", "0") == "1"
        self.sync_reason = "MXNET_SERVE_SYNC=1" if self.sync_mode \
            else None
        self._progs = None
        self._pool_bytes = 0
        if not self.sync_mode:
            try:
                self._progs = PoolPrograms(
                    model, self.pool_sizes[0], self.T, temperature,
                    top_k, eos_id, weights,
                    telemetry_label=self.telemetry_label,
                    page_size=self.page_size, num_pages=num_pages,
                    kv_dtype=self.kv_dtype)
            except MXNetError as e:
                # models the slot-pool gate rejects still serve, one
                # request at a time, through the kv_generate fallback
                self.sync_mode = True
                self.sync_reason = str(e)
        if self.sync_mode and self.hbm_budget is not None:
            # the kv_generate fallback holds no resident pool and
            # allocates per-request caches inside its own executables —
            # the budget machinery has nothing to meter there.  Say so
            # loudly: a silently inert limit is worse than none
            import warnings

            warnings.warn(
                f"DecodeServer hbm_budget={self.hbm_budget} is NOT "
                "enforced in sync mode (kv_generate fallback"
                f"{'' if self.sync_reason is None else ': ' + self.sync_reason}"
                ") — per-request decode caches are unmetered",
                stacklevel=2)
        if not self.sync_mode:
            # price the MINIMUM USABLE configuration before allocating
            # anything: the smallest pool plus the smallest admission
            # wave's prefill scratch (every request must pass through
            # one admission, so a budget that fits the pool alone would
            # construct a server that fails every submit) — a budget
            # the config can never fit is a constructor error, not a
            # first-request teardown
            from .engine import admit_scratch_bytes

            self._check_budget(
                self.pool_sizes[0],
                scratch=admit_scratch_bytes(self._progs,
                                            self.admit_sizes[0]),
                what=f"initial pool ({self.pool_sizes[0]} slots) plus "
                     f"the smallest admission wave's "
                     f"(A={self.admit_sizes[0]}) prefill scratch")
        self._state = None if self.sync_mode \
            else pool_state_init(self._progs)
        if self._state is not None:
            self._account_pool()
        # host-side page bookkeeping (scheduler-thread-only, like the
        # slot table): the free-list allocator, per-slot page-table
        # rows, the set of slots mid-chunked-prefill (their reserved
        # pages are masked OUT of the step's table until the final
        # chunk activates them), and the COW prefix index
        self._pages = None if self.sync_mode \
            else PagePool(self._progs.num_pages)
        self._slot_pages = [[] for _ in range(self.pool_sizes[0])]
        self._chunk_slots = set()
        self._chunking = deque()   # {"req", "slot", "off"} records
        self._prefix = _PrefixIndex(self._progs.page, self._pages) \
            if not self.sync_mode and self.prefix_cache_enabled \
            else None

        # scheduler bookkeeping (single scheduler thread; submit() is
        # the only cross-thread writer and it only touches _pending)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending = deque()
        self._stopping = False
        self._slots = [None] * self.pool_sizes[0]   # slot -> _Request
        self._inflight = deque()   # (kind, arrays, slot_snapshot/req)
        self._next_id = 0
        self._steps = 0
        self._occupied_lane_steps = 0
        self._capacity_lane_steps = 0   # sums len(_slots) per step, so
        # occupancy stays honest across pool growth (S changes mid-run)
        # per-server dispatch accounting: a dict-API view over the
        # telemetry registry (the module-level serve_counters aggregate
        # is also incremented, under its shared lock)
        self.counters = _CounterView(self.telemetry_label)
        self._stats_emitted = False
        self._thread = None
        telemetry.emit(
            "serve_config", server=self.telemetry_label,
            pool_sizes=list(self.pool_sizes),
            admit_sizes=list(self.admit_sizes),
            prefill_buckets=list(self.prefill_buckets),
            max_total_len=self.T, sync_mode=self.sync_mode,
            sync_reason=self.sync_reason,
            hbm_budget=self.hbm_budget, pool_bytes=self._pool_bytes,
            default_deadline=self.default_deadline,
            step_timeout=self.step_timeout,
            page_size=self.page_size,
            num_pages=None if self.sync_mode
            else self._progs.num_pages,
            kv_dtype=self.kv_dtype,
            # the priced per-page byte cost at kv_dtype — what
            # --check-serve's dtype-aware capacity check re-derives
            # pool_bytes from (None in sync mode: no resident pool)
            page_bytes=None if self.sync_mode
            else self._progs.page_bytes(),
            prefix_cache=self.prefix_cache_enabled,
            spec=self.spec_enabled, spec_depth=self.spec_depth,
            spec_sizes=list(self.spec_sizes))
        if autostart:
            self.start()

    # -- public API ------------------------------------------------------ #
    def start(self):
        """Start the background scheduler thread (no-op if one is
        already running), plus its watchdog: the watchdog fails every
        in-flight stream with the underlying error when the scheduler
        thread dies without cleanup, or when one pump wedges past
        ``step_timeout`` / ``MXNET_SERVE_STEP_TIMEOUT`` — no consumer
        ever blocks forever on a dead pump.  ``autostart=False`` + a
        later ``start()`` lets the owner warm the compiled programs
        pump-driven first, then hand the loop to the thread —
        ``benchmark/serve_bench.py`` uses this to keep compiles off
        the measured clock."""
        with self._work:
            if self._stopping:
                raise self._closed_error()
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-serve", daemon=True)
            self._thread.start()
            if self._watchdog is None or not self._watchdog.is_alive():
                self._watchdog = threading.Thread(
                    target=self._watch, name="mxnet-serve-watchdog",
                    daemon=True)
                self._watchdog.start()

    def _closed_error(self):
        """The submit/start error after the server stopped: names the
        scheduler's fatal error when it died, plain "closed" after a
        clean close()."""
        if self._fatal is not None:
            return MXNetError(
                f"server failed and stopped serving: {self._fatal}")
        return MXNetError("server is closed")

    def submit(self, prompt_tokens, max_new_tokens=32, seed=0,
               nowait=False, on_token=None, deadline=None):
        """Queue one request; returns its :class:`TokenStream`.

        ``deadline`` (seconds, default ``default_deadline`` /
        ``MXNET_SERVE_DEADLINE``) is the request's wall-clock budget
        measured from submit: when it expires the sequence is retired
        DEVICE-SIDE at the next step boundary (the per-slot deadline
        rides the slot-state vector; no extra dispatch) with the
        tokens produced so far and reason ``deadline_exceeded``; a
        request whose deadline lapses while still queued is retired at
        the admission boundary without occupying a slot.

        Blocks while ``max_pending`` requests are already queued
        (``nowait=True`` raises instead — pool-full backpressure is a
        visible error, not an unbounded queue)."""
        prompt = onp.asarray(
            prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
            else prompt_tokens, dtype=onp.int32).reshape(-1)
        if prompt.size == 0:
            raise MXNetError("empty prompt")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        # prompts past the largest pinned prefill bucket are NOT
        # rejected: chunked prefill streams them in over several
        # dispatches — the only hard limit is the pool cache length
        if prompt.size + max_new_tokens > self.T:
            raise MXNetError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the pool cache length "
                f"{self.T}")
        if not self.sync_mode:
            # a request that can NEVER be paged in (more pages than
            # the pool will ever hold, reachable only with an explicit
            # small num_pages=) is a caller error here, not an
            # admission loop that spins forever
            need = self._progs.pages_for(prompt.size + max_new_tokens)
            cap = self._pages.num_pages if self._num_pages_fixed \
                else self.pool_sizes[-1] * self._progs.maxp
            if need > cap:
                raise MXNetError(
                    f"request needs {need} KV pages "
                    f"({prompt.size} prompt + {max_new_tokens} new "
                    f"tokens at page_size={self._progs.page}) but the "
                    f"page pool holds at most {cap} — raise "
                    "num_pages= or lower max_new_tokens")
        seed = int(seed)
        if not -2 ** 31 <= seed < 2 ** 31:
            # the slot pool carries the seed as a traced int32 operand;
            # rejecting it HERE keeps an oversized seed a caller error
            # instead of an OverflowError on the scheduler thread
            raise MXNetError(
                f"seed {seed} does not fit int32 — fold larger seeds "
                "on the host before submitting")
        if deadline is None:
            deadline = self.default_deadline
        if deadline is not None and deadline <= 0:
            raise MXNetError(
                f"deadline {deadline} must be positive seconds")
        abs_deadline = None if deadline is None \
            else self._clock() + deadline
        with self._work:
            if self._stopping:
                raise self._closed_error()
            while len(self._pending) >= self.max_pending:
                if nowait:
                    raise MXNetError(
                        f"backpressure: {len(self._pending)} requests "
                        f"pending (max_pending={self.max_pending})")
                if self._thread is None:
                    # no scheduler thread to drain the queue — blocking
                    # here would deadlock the pump()-driving thread
                    raise MXNetError(
                        f"backpressure: {len(self._pending)} requests "
                        f"pending (max_pending={self.max_pending}) and "
                        "no scheduler thread (autostart=False) — call "
                        "pump() to drain, or submit(nowait=True)")
                self._work.wait(0.05)
                if self._stopping:
                    raise self._closed_error()
            stream = TokenStream(self._next_id, self._detok, on_token)
            self._next_id += 1
            req = _Request(prompt, int(max_new_tokens), int(seed),
                           stream, deadline=abs_deadline)
            stream._cancel_hook = lambda: self._cancel(req)
            self._pending.append(req)
            self._work.notify_all()
        return stream

    def _count(self, key, n=1):
        self.counters.inc(key, n)
        _bump(key, n)

    def _slot_spec_depth(self, req):
        """The speculation-depth cap scattered into a slot's state row
        at admission (0 = never speculate; the device clamps accepted
        drafts to it even if a buggy drafter over-proposes)."""
        return self.spec_depth if self.spec_enabled else 0

    def reset_counters(self):
        """Zero the per-server dispatch counters AND the step/occupancy
        ledger, so a measurement window opened after a warm-up phase
        (``benchmark/serve_bench.py`` warms the whole admission-bucket
        ladder) reports the window's own occupancy, undiluted by the
        warm-up's idle lanes."""
        for k in self.counters:
            self.counters[k] = 0
        self._steps = 0
        self._occupied_lane_steps = 0
        self._capacity_lane_steps = 0

    def stats(self):
        """Structured scheduler/occupancy/latency snapshot: the
        historical counters plus the per-server registry instruments
        (dispatch counters, TTFT / inter-token-gap / queue-wait
        histogram summaries) — the serving face of
        ``telemetry.snapshot()``."""
        S = len(self._slots)
        acc = self.counters["draft_accepted"]
        rej = self.counters["draft_rejected"]
        return {
            "server": self.telemetry_label,
            "num_slots": S,
            "steps": self._steps,
            # speculative-decoding face: the per-server draft ledger
            # plus the accept rate the benches report (accepted +
            # rejected == proposed is the --check-serve invariant)
            "spec": self.spec_enabled,
            "spec_depth": self.spec_depth,
            "draft_accepted": acc,
            "draft_rejected": rej,
            "draft_accept_rate": acc / (acc + rej)
            if (acc + rej) else 0.0,
            "occupancy": (self._occupied_lane_steps /
                          self._capacity_lane_steps
                          if self._capacity_lane_steps else 0.0),
            "pending": len(self._pending),
            "in_flight": sum(r is not None for r in self._slots),
            "sync_mode": self.sync_mode,
            # accountant-backed resident-pool bytes (0 in sync mode —
            # the kv_generate fallback holds no resident cache); never
            # read from self._state here, whose buffers may be donated
            # to an in-flight dispatch on the scheduler thread
            "pool_bytes": self._pool_bytes,
            "hbm_budget": self.hbm_budget,
            # pool storage dtype + the priced per-page cost: together
            # with pages_total they re-derive pool_bytes, the
            # --check-serve dtype-aware capacity identity
            "kv_dtype": self.kv_dtype,
            "page_bytes": None if self.sync_mode
            else self._progs.page_bytes(),
            # page-pool occupancy (0/None in sync mode: no pool)
            "page_size": None if self.sync_mode else self._progs.page,
            "pages_total": 0 if self._pages is None
            else self._pages.num_pages,
            "pages_in_use": 0 if self._pages is None
            else self._pages.in_use,
            "prefix_nodes": 0 if self._prefix is None
            else len(self._prefix),
            "counters": dict(self.counters),
            "ttft": self._tele["ttft"].summary(),
            "token_gap": self._tele["gap"].summary(),
            "queue_wait": self._tele["wait"].summary(),
        }

    def close(self, drain=True, timeout=60.0):
        """Stop the scheduler.  ``drain=True`` serves everything already
        submitted first; otherwise queued/in-flight requests fail with
        a server-closed error.  Deadline arithmetic is monotonic — a
        wall-clock (NTP) step during the drain cannot turn the budget
        into an instant or an infinite timeout."""
        deadline = time.monotonic() + timeout
        if drain:
            while (self._pending or
                   any(r is not None for r in self._slots) or
                   self._inflight):
                if self._thread is None or not self._thread.is_alive():
                    # no scheduler left to drain the backlog — either
                    # autostart=False, or a PRIOR close() timed out and
                    # the thread has since exited at its _stopping
                    # check with work outstanding; pump from here so
                    # "call close() again" actually finishes the drain
                    if not self.pump():
                        break
                elif time.monotonic() > deadline:
                    raise MXNetError("close(drain=True) timed out")
                else:
                    time.sleep(0.002)
        with self._work:
            self._stopping = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(
                timeout=max(deadline - time.monotonic(), 0.1))
            if self._thread.is_alive():
                # the scheduler is mid-pump (e.g. a pool-growth retrace
                # compiling) and owns _slots/_inflight — tearing them
                # down under it would double-route token waves.  It
                # exits at its next _stopping check; call close() again
                # to finish teardown.
                raise MXNetError(
                    "close() timed out waiting for the scheduler "
                    "thread (still inside a dispatch/retrace); it "
                    "stops at the next step boundary — call close() "
                    "again to finish teardown")
        if self._watchdog is not None:
            self._watchdog.join(timeout=1.0)   # exits on _stopping
        self._flush_drain(final=True)
        self._emit_stats()
        self._teardown(MXNetError("server closed"), reason="closed")

    def _emit_stats(self):
        """One ``serve_stats`` event per server lifetime (at close):
        the final counters + occupancy + latency summaries, so a
        recorded JSONL alone can re-check the one-dispatch-per-step
        discipline (``tools/telemetry_report.py --check-serve``)."""
        if self._stats_emitted:
            return
        self._stats_emitted = True
        telemetry.emit("serve_stats", **self.stats())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc == (None, None, None))

    # -- scheduler ------------------------------------------------------- #
    def pump(self):
        """One scheduler round: cancellations, admissions, one step
        dispatch, drain.  Returns True if any work happened (False =
        fully idle: nothing pending, nothing in flight — the loop
        thread sleeps on that)."""
        fault_point("serve.pump", server=self.telemetry_label)
        # cancellations FIRST: a cancelled slot frees at this step
        # boundary, so the admission below can re-fill it in the same
        # pump — no wasted masked lane, no extra dispatch
        worked = self._process_cancels()
        if self.sync_mode:
            return self._pump_sync() or worked
        worked |= self._admit_pending()
        stepped = False
        # slots mid-chunked-prefill don't step (their lanes activate at
        # the final chunk); only genuinely live lanes justify a dispatch
        if self._live_slots():
            drafts = None
            if self.spec_enabled:
                # drafts must chain off each slot's NEWEST device
                # token, which is still in flight until the previous
                # dispatch drains — speculation trades the one-dispatch
                # host/device overlap for multi-token dispatches
                # (docs/SERVING.md); draining here may retire slots, so
                # the liveness check repeats below
                worked |= self._flush_drain()
                if self._live_slots():
                    drafts = self._build_drafts()
            if drafts:
                self._dispatch_verify(drafts)
                worked = stepped = True
            elif self._live_slots():
                self._dispatch_step()
                worked = stepped = True
        # drain PREVIOUS dispatches' readbacks: while stepping, the
        # newest dispatch stays in flight so the device computes it
        # while the host routes the older (S,)-sized arrays; once the
        # loop stops stepping, everything drains so streams finish
        worked |= self._flush_drain(keep=1 if stepped else 0)
        return worked

    def _live_slots(self):
        return any(r is not None and i not in self._chunk_slots
                   for i, r in enumerate(self._slots))

    def _loop(self):
        while True:
            with self._work:
                if self._stopping:
                    return
            self._pump_t0 = self._clock()   # the watchdog's wedge gauge
            try:
                worked = self.pump()
            except Exception as e:
                # a runtime dispatch failure (device OOM, XLA error, a
                # growth retrace) must not silently kill the scheduler
                # thread and hang every consumer: fail all outstanding
                # streams with the error and stop serving
                self._fail_all(e)
                return
            finally:
                self._pump_t0 = None
            if not worked:
                with self._work:
                    if self._stopping:
                        return
                    if not self._pending and not self._inflight:
                        self._work.wait(0.05)

    def _watch_dispatch(self, fn):
        """Re-arm the wedge gauge for one dispatch — or SUSPEND it when
        ``fn`` has never compiled: a legitimate first-request /
        pool-growth jit compile can take far longer than any sane
        ``step_timeout``, and the watchdog must not kill a healthy
        server for it.  (Run on the scheduler thread only; _pump_t0 is
        cleared by _loop after the pump either way.)"""
        if self._pump_t0 is None:
            return   # pump-driven (no loop thread): nothing to gauge
        cache_size = getattr(fn, "_cache_size", None)
        if cache_size is not None and cache_size() == 0:
            self._pump_t0 = None     # cold program: compile, not wedge
        else:
            self._pump_t0 = self._clock()   # per-dispatch budget

    def _watch(self):
        """Scheduler watchdog (daemon, started next to the loop
        thread): fails all in-flight streams when the pump thread DIES
        without running its own failure path (a BaseException, a
        crashed C extension — an Exception inside pump() is already
        handled by ``_loop``), or when one pump WEDGES past
        ``step_timeout`` (a hung dispatch: the thread cannot be
        recovered, but every consumer gets the error instead of
        blocking forever).  Exits when the server stops."""
        while True:
            with self._work:
                if self._stopping:
                    return
            th = self._thread
            if th is not None and not th.is_alive():
                with self._work:
                    if self._stopping:
                        return   # clean close() raced the aliveness
                        # check: the thread exited BECAUSE we stopped
                self._watchdog_fire("scheduler thread died without "
                                    "running its failure path")
                return
            t0 = self._pump_t0
            if self.step_timeout is not None and t0 is not None \
                    and self._clock() - t0 > self.step_timeout:
                self._watchdog_fire(
                    f"scheduler pump wedged for more than "
                    f"step_timeout={self.step_timeout}s "
                    "(MXNET_SERVE_STEP_TIMEOUT) — a dispatch is hung")
                return
            time.sleep(0.05)

    def _watchdog_fire(self, why):
        telemetry.emit("watchdog_fired", server=self.telemetry_label,
                       reason=why)
        telemetry.counter("serve_watchdog_fired_total",
                          server=self.telemetry_label).inc()
        self._fail_all(MXNetError(
            f"serve watchdog fired: {why}; all in-flight streams "
            "failed"))

    def _fail_all(self, exc):
        err = exc if isinstance(exc, MXNetError) else \
            MXNetError(f"serving loop failed: {exc!r}")
        self._fatal = err   # submit()/start() raise this from now on
        with self._work:
            self._stopping = True
            self._work.notify_all()
        self._inflight.clear()   # readbacks are dropped, not routed
        self._teardown(err)

    # cancellation --------------------------------------------------------- #
    def _cancel(self, req):
        """Cross-thread cancellation entry (``TokenStream.cancel``).
        A queued request is dropped and finished HERE; an admitted one
        is only FLAGGED — its slot frees on the scheduler thread at
        the next step boundary (``_process_cancels``), so co-resident
        lanes never see a mid-step state edit.  Idempotent; False once
        the request already retired."""
        with self._work:
            if req.retired or req.stream.done:
                return False
            queued = req in self._pending
            if self.sync_mode and not queued:
                # sync fallback mid-kv_generate: there are no step
                # boundaries to retire at, so cancellation cannot take
                # effect — report failure rather than lie (the
                # slot-pool path is where cancel is real;
                # docs/SERVING.md)
                return False
            already = req.cancelled
            req.cancelled = True
            in_queue = False
            if not already and queued:
                self._pending.remove(req)
                in_queue = True
            self._work.notify_all()
        if in_queue:
            self._retire_aside(req, "cancelled")
        return True

    def _process_cancels(self):
        """Free cancelled requests' slots at the step boundary (the
        scheduler thread; also the pump-driven path).  The device lane
        itself is left alone — like any retired slot it keeps
        computing masked until re-admission overwrites it — so the
        retirement costs ZERO extra dispatches and cannot perturb
        co-resident streams."""
        with self._lock:
            hit = [(i, r) for i, r in enumerate(self._slots)
                   if r is not None and r.cancelled]
            for i, _r in hit:
                self._slots[i] = None
            if hit:
                self._work.notify_all()
        for i, r in hit:
            self._drop_chunk_record(i)
            self._free_slot_pages(i)
            self._retire_aside(r, "cancelled")
        # queued cancellations normally drop in _cancel; this sweeps
        # any that raced the pending-pop
        with self._lock:
            stale = [r for r in self._pending if r.cancelled]
            for r in stale:
                self._pending.remove(r)
        for r in stale:
            self._retire_aside(r, "cancelled")
        return bool(hit) or bool(stale)

    def _retire_aside(self, req, reason):
        """Finish a stream OUTSIDE the normal drain path (cancelled, or
        deadline-lapsed while queued): the stream seals with whatever
        tokens arrived, the span closes with ``reason``."""
        req.stream._cancelled = reason == "cancelled"
        req.stream._finish()
        self._observe_retire(req, reason)

    def _teardown(self, err, reason="error"):
        """Fail every queued and in-flight request with ``err``.  The
        snapshot-and-clear runs under the lock; streams are finished
        OUTSIDE it — _finish wakes consumer threads (and on_token
        callers) that may immediately re-enter submit()/stats()."""
        from ..telemetry.memory import ACCOUNTANT

        # ordering matters: flag FIRST, then release — a concurrent
        # wedged dispatch that assigns self._state after our None sees
        # the flag and releases its own result (no re-pin window)
        self._torn = True
        # the pool buffers die with the server: RELEASE them (drop the
        # state refs so the device memory is actually freed, not just
        # unaccounted) and retire the ledger entry + stats() mirror
        # together, so a closed server's stats()["pool_bytes"] agrees
        # with the zeroed device_bytes gauge AND with the allocator
        # (idempotent: close() after a failed scheduler lands here
        # twice)
        self._state = None
        ACCOUNTANT.drop("serve.kv_pool", self.telemetry_label)
        self._pool_bytes = 0
        # page bookkeeping dies with the pool buffers (idempotent):
        # slot rows, chunk records and the prefix index all release
        # their refs so a closed server reports pages_in_use == 0
        if self._pages is not None:
            self._chunking.clear()
            self._chunk_slots.clear()
            for i in range(len(self._slot_pages)):
                self._free_slot_pages(i)
            if self._prefix is not None:
                self._prefix.drop_all()
            self._tele["pages"].set(0)
        with self._lock:
            dropped = list(self._pending)
            self._pending.clear()
            leftover = [r for r in self._slots if r is not None]
            self._slots = [None] * len(self._slots)
            self._work.notify_all()
        for req in dropped + leftover:
            req.stream._finish(err)
            self._observe_retire(req, reason)

    # memory budget ------------------------------------------------------- #
    def _account_pool(self):
        """Register the pool state's exact bytes with the process-wide
        memory accountant (``device_bytes{subsystem="serve.kv_pool",
        device=}`` gauge + one ``device_memory`` event per change) —
        called at init and after each growth, never per step.  The
        ledger stores byte counts only, so the steady state's donated
        cache buffers (same shapes every step) stay correctly
        accounted without re-registration."""
        from ..telemetry.memory import ACCOUNTANT, nbytes_of

        self._pool_bytes = nbytes_of(self._state)
        ACCOUNTANT.set("serve.kv_pool", self.telemetry_label,
                       self._state)

    def _check_budget(self, num_slots, scratch=0, what="",
                      num_pages=None):
        """Refuse device allocations the HBM budget cannot hold, with a
        clean error naming requested vs available bytes (instead of an
        allocator OOM mid-dispatch).  ``num_slots`` prices the resident
        pool at that size (``num_pages`` overrides the dense-equivalent
        default page count); ``scratch`` adds transient bytes
        (admission prefill caches) on top of it."""
        if self.hbm_budget is None:
            return
        from ..telemetry.memory import format_bytes
        from .engine import pool_state_bytes

        projected = pool_state_bytes(self._progs, num_slots,
                                     num_pages=num_pages) \
            + scratch
        if projected <= self.hbm_budget:
            return
        requested = projected - self._pool_bytes
        available = max(self.hbm_budget - self._pool_bytes, 0)
        raise MXNetError(
            f"serve HBM budget exceeded: {what or 'allocation'} "
            f"requests {format_bytes(requested)} on top of the "
            f"{format_bytes(self._pool_bytes)} resident pool, but only "
            f"{format_bytes(available)} of the "
            f"{format_bytes(self.hbm_budget)} budget "
            f"(hbm_budget= / MXNET_SERVE_HBM_BUDGET) remains — raise "
            "the budget, pin smaller MXNET_SERVE_POOL_SIZES / "
            "MXNET_SERVE_ADMIT_SIZES, or lower max_total_len")

    # admissions --------------------------------------------------------- #
    def _take_pending(self):
        with self._lock:
            if not self._pending:
                return None
            req = self._pending.popleft()
            self._work.notify_all()
            return req

    def _maybe_grow(self):
        """Grow the pool to the next pinned size when the backlog wants
        more lanes than exist (retrace happens at most
        ``len(pool_sizes) - 1`` times, never per request)."""
        from .engine import PoolPrograms, pool_state_grow

        S = len(self._slots)
        busy = sum(r is not None for r in self._slots)
        want = busy + len(self._pending)
        bigger = [s for s in self.pool_sizes if s > S]
        if not bigger or want <= S:
            return
        new_s = S
        for s in bigger:
            new_s = s
            if s >= want:
                break
        # consult the memory accountant BEFORE compiling/allocating the
        # larger pool: an over-budget growth is a clean refusal naming
        # the shortfall, not an allocator OOM halfway through a retrace.
        # Priced as old + new pools RESIDENT TOGETHER: pool_state_grow
        # pads the old state into the new one, so both live until the
        # copy completes — the transient peak, not the settled size.
        # The refusal is deliberately LOUD (ISSUE 10 acceptance): a
        # budget the pinned pool ladder outgrows is a sizing error the
        # operator must see and fix (pin smaller pool sizes, or raise
        # the budget — tools/memory_report.py prices configs offline),
        # not a condition to silently serve degraded through
        # an explicitly pinned page count stays pinned across growth;
        # the dense-equivalent default rescales with the slot count
        new_pages = self._pages.num_pages if self._num_pages_fixed \
            else new_s * self._progs.maxp
        self._check_budget(new_s, scratch=self._pool_bytes,
                           what=f"pool growth {S} -> {new_s} slots",
                           num_pages=new_pages)
        # growth compiles (eager state pad now, fresh step/admit
        # programs at their first dispatch): suspend the watchdog's
        # wedge gauge for the rest of this pump — a retrace is slow,
        # not wedged
        self._pump_t0 = None
        progs = PoolPrograms(self.model, new_s, self.T,
                             self.temperature, self.top_k, self.eos_id,
                             self.weights,
                             telemetry_label=self.telemetry_label,
                             page_size=self.page_size,
                             num_pages=new_pages,
                             kv_dtype=self.kv_dtype)
        # the old pool's in-flight readbacks refer to old slot indices
        # and page ids; they stay valid — slots and pages only ever grow
        self._progs = progs
        self._state = pool_state_grow(self._state, new_s,
                                      new_pages=new_pages)
        self._account_pool()
        if new_pages > self._pages.num_pages:
            self._pages.grow(new_pages)
        with self._lock:
            self._slots.extend([None] * (new_s - S))
        self._slot_pages.extend([] for _ in range(new_s - S))
        self._count("pool_grows")

    def _admit_pending(self):
        """Wave-building batched admission: gather ALL currently
        pending requests the free slots can take (capped at the
        largest pinned ``A`` bucket), PLAN each one against the page
        pool / prefix cache, and dispatch each mode in bulk — prefill
        admissions as ONE bucketed ``(A, P)`` dispatch, prefix-cache
        hits as ONE no-forward hit dispatch, long prompts as chunked
        prefill records the pump streams in.  A burst of k arrivals at
        a step boundary costs 1-2 dispatches, not k.  The outer loop
        spills a backlog larger than the biggest ``A`` bucket (or than
        the free slots) into follow-up dispatches in the same pump."""
        admitted = may_retire = False
        self._maybe_grow()
        cap = self.admit_sizes[-1]
        while True:
            free = [i for i, r in enumerate(self._slots)
                    if r is None and i not in self._chunk_slots]
            if not free:
                break
            limit = min(len(free), cap)
            if self.hbm_budget is not None:
                # price the wave's admission scratch BEFORE popping it
                # into the slot table: a refusal here leaves the
                # requests pending and the slots free (a raise after
                # slot-recording would strand never-admitted lanes that
                # close(drain=True) then pumps forever).  The wave is
                # CLAMPED to the largest pinned A bucket the budget can
                # hold next to the current pool — a burst that would
                # only overflow at the big bucket admits in smaller
                # waves instead of failing; only a pool too large for
                # even the smallest bucket (reachable after growth)
                # raises.  The pop below is capped at the clamped size,
                # so a submit racing in can't inflate the priced A.
                from .engine import admit_scratch_bytes, \
                    pool_state_bytes

                with self._lock:
                    limit = min(limit, len(self._pending))
                if not limit:
                    break
                progs = self._progs
                resident = pool_state_bytes(
                    progs, len(self._slots),
                    num_pages=self._pages.num_pages)
                # the admit scratch is a DENSE native-dtype prefill
                # cache regardless of the pool's kv_dtype — priced as
                # such, so an int8 pool's smaller resident footprint
                # can't hide the full-size admission spike
                usable = [a for a in self.admit_sizes
                          if resident + admit_scratch_bytes(progs, a)
                          <= self.hbm_budget]
                if not usable:
                    A = self.admit_sizes[0]
                    self._check_budget(
                        len(self._slots),
                        scratch=admit_scratch_bytes(progs, A),
                        num_pages=self._pages.num_pages,
                        what=f"admission wave of {limit} "
                             f"(A={A} prefill scratch)")
                limit = min(limit, usable[-1])
            # pop + record into the slot table ATOMICALLY: a request
            # must never be invisible to close(drain=True)'s "anything
            # outstanding?" predicate (or to _fail_all) while its
            # admission dispatch is still being built.  Cancelled or
            # already-deadline-lapsed requests retire HERE, at the
            # admission boundary, without ever occupying a slot.
            wave, dropped = [], []
            now = self._clock()
            with self._lock:
                while self._pending and len(wave) < limit:
                    req = self._pending.popleft()
                    if req.cancelled or (req.deadline is not None
                                         and now >= req.deadline):
                        dropped.append(req)
                        continue
                    slot = free[len(wave)]
                    self._slots[slot] = req
                    wave.append((slot, req))
                if wave or dropped:
                    self._work.notify_all()
            for req in dropped:
                self._retire_aside(
                    req, "cancelled" if req.cancelled
                    else "deadline_exceeded")
            admitted |= bool(dropped)
            if not wave:
                if dropped:
                    continue   # the backlog behind the drops may fit
                break
            # reserve pages + classify each popped request (prefill
            # admit / prefix-cache hit / chunked prefill).  A pool that
            # can't cover a request right now unwinds IT and everything
            # behind it back to the queue front, in order — retiring
            # slots free pages and the next pump retries.
            plans, failed = [], None
            for k, (slot, req) in enumerate(wave):
                plan = self._plan_admission(req, slot)
                if plan is None:
                    failed = wave[k:]
                    break
                plans.append(plan)
            if failed is not None:
                with self._lock:
                    for slot, _req in failed:
                        self._slots[slot] = None
                    for _slot, req in reversed(failed):
                        self._pending.appendleft(req)
            admit_wave = [(p["slot"], p["req"]) for p in plans
                          if p["mode"] == "admit"]
            hit_wave = [p for p in plans if p["mode"] == "hit"]
            for p in plans:
                if p["mode"] == "chunk":
                    self._chunk_slots.add(p["slot"])
                    self._chunking.append(
                        {"req": p["req"], "slot": p["slot"],
                         "off": p["off"], "zero": p["zero"]})
            # hits dispatch FIRST: a COW source page another plan's
            # eviction freed and re-allocated this wave must be copied
            # before any admit/chunk dispatch can overwrite it (the
            # device stream is FIFO)
            if hit_wave:
                self._dispatch_hits(hit_wave)
            if admit_wave:
                self._dispatch_admit(admit_wave)
                may_retire |= any(r.max_new == 1
                                  for _, r in admit_wave)
            admitted |= bool(plans)
            if failed is not None:
                break
        chunked, chunk_retire = self._pump_chunks()
        if may_retire or chunk_retire:
            # a 1-token budget retires INSIDE the admission executable;
            # read the (first_tok, done) flags back now so its slot
            # frees before the step-dispatch decision — no wasted
            # dispatch.  Every other admission drains lazily with the
            # step readbacks, off the hot path (an EOS on the very
            # first token costs at most one masked-lane step).
            self._drain_admits()
        return admitted or chunked

    def _dispatch_admit(self, wave):
        """ONE bucketed (A, P) admission dispatch for a wave of
        ``(slot, request)`` pairs: A = smallest pinned wave bucket that
        fits the wave, P = smallest pinned prompt bucket that fits the
        wave's longest prompt (the admission planner routes longer
        prompts to chunked prefill, so one always exists).  Rows beyond
        the wave are masked no-ops on device; the prefill stream lands
        in the wave's reserved pages via the page-row operand."""
        fault_point("serve.admit", server=self.telemetry_label,
                    wave=len(wave))
        A = _bucket_for(self.admit_sizes, len(wave))
        P = _bucket_for(self.prefill_buckets,
                        max(req.prompt.size for _, req in wave))
        # the A-lane prefill scratch was budget-checked in
        # _admit_pending BEFORE the wave was popped into the slot
        # table (wave size <= the priced limit, so A here never
        # exceeds the checked bucket)
        fn = self._progs.admit_fn(A, P)
        self._watch_dispatch(fn)
        prompts = onp.zeros((A, P), onp.int32)
        # idle rows: valid=0 (their scatter drops on device); true_len
        # stays 1 so the per-row last-index gather reads a real column
        meta = onp.zeros((A, schema.meta_width("admit")), onp.int32)
        meta[:, schema.meta_col("admit", "true_len")] = 1
        # per-row wall-clock deadlines (server-epoch seconds; +inf =
        # none), scattered into the slot-state deadline vector the
        # step checks device-side
        dls = onp.full((A,), onp.inf, onp.float32)
        # reserved-page rows: idle rows and tail pages past a row's
        # reservation carry the sentinel, so their scatter drops
        npb = -(-P // self._progs.page)
        pages = onp.full((A, npb), self._progs.num_pages, onp.int32)
        # int8 recycled-page reset operand: EVERY page the wave
        # reserved (decode-frontier pages included — those are first
        # written by the step/verify RMWs, which floor at the page's
        # resident scale).  The executable zeroes their scales before
        # its own page writes; f32 pools ignore the operand.
        zpages = onp.full((A, self._progs.maxp), self._progs.num_pages,
                          onp.int32)
        for i, (slot, req) in enumerate(wave):
            n = req.prompt.size
            prompts[i, :n] = req.prompt
            meta[i] = schema.meta_row(
                "admit", valid=1, true_len=n, slot=slot,
                stop_pos=n + req.max_new - 1, seed=req.seed,
                spec_depth=self._slot_spec_depth(req))
            if req.deadline is not None:
                dls[i] = req.deadline - self._epoch
            row = self._slot_pages[slot]
            k = min(npb, len(row))
            pages[i, :k] = row[:k]
            zpages[i, :len(row)] = row
        # request-span admission fields + one serve_admit event per
        # dispatch (waves are step-boundary-rare, not per-token)
        now = time.perf_counter()
        S = len(self._slots)
        busy = sum(r is not None for r in self._slots)
        occ = busy / S if S else 0.0
        for _slot, req in wave:
            wait = now - req.stream.submit_time
            req.span.update(queue_wait_s=wait, wave=len(wave),
                            a_bucket=A, p_bucket=P,
                            occupancy_at_admit=occ)
            self._tele["wait"].observe(wait)
        telemetry.emit("serve_admit", server=self.telemetry_label,
                       wave=len(wave), a_bucket=A, p_bucket=P,
                       pool=S, occupancy=round(occ, 4))
        param_vals, q8, sw = self._progs.operands
        with telemetry.annotation("mx:serve:admit"):
            new_state, (first, done) = fn(param_vals, prompts, meta,
                                          dls, pages, zpages,
                                          *self._state)
        self._state = new_state
        if self._torn:
            # the watchdog tore the server down while this dispatch was
            # wedged: the accountant already reported the pool freed —
            # drop the late result instead of re-pinning it
            self._state = None
            return
        self._count("admit_dispatches")
        self._inflight.append(("admit", (first, done), list(wave)))
        if self._prefix is not None:
            # index the wave's FULL prompt pages for future COW hits
            # (device-written by the dispatch just queued; any
            # consumer's read is a later dispatch on the same stream)
            for slot, req in wave:
                self._prefix.register(req.prompt, req.prompt.size,
                                      self._slot_pages[slot])

    # paged admission planning ------------------------------------------- #
    def _alloc_pages(self, n, protect=()):
        """All-or-nothing page reservation, evicting LRU prefix-cache
        entries (never ``protect``) when the free list runs dry."""
        got = self._pages.alloc(n)
        if got is None and self._prefix is not None:
            self._prefix.evict(n - self._pages.free_pages,
                               protect=protect)
            got = self._pages.alloc(n)
        return got

    def _free_slot_pages(self, slot):
        """Release one slot's page-table refs (idempotent: the row is
        cleared first).  Shared pages survive while the prefix index
        or another slot still holds them — that's the refcount."""
        row = self._slot_pages[slot]
        self._slot_pages[slot] = []
        for p in row:
            self._pages.decref(p)

    def _drop_chunk_record(self, slot):
        """Forget a mid-chunked-prefill slot (cancel/teardown paths)."""
        if slot in self._chunk_slots:
            self._chunk_slots.discard(slot)
            for rec in list(self._chunking):
                if rec["slot"] == slot:
                    self._chunking.remove(rec)

    def _plan_admission(self, req, slot):
        """Decide how one popped request enters its slot, reserving its
        pool pages up front (ALL ``ceil((L+max_new)/page)`` of them —
        all-or-nothing, so a half-admitted pool can never deadlock):

        - ``admit``  — one bucketed prefill dispatch (no cached prefix,
          prompt fits the largest pinned bucket);
        - ``hit``    — the prefix cache covers every prompt token but
          (at most) the last: shared pages map READ-ONLY into the row,
          ZERO prefill dispatches, at most one COW page copy;
        - ``chunk``  — the prompt (or its uncached suffix) streams in
          over chunked-prefill dispatches.

        Returns ``None`` when the pool can't supply the pages right
        now (the caller re-queues the request and retries next pump,
        after retirements free pages)."""
        progs = self._progs
        PG = progs.page
        L = int(req.prompt.size)
        need = progs.pages_for(L + req.max_new)
        m, shared = (self._prefix.match(req.prompt)
                     if self._prefix is not None else (0, []))
        if m and m * PG >= L - 1:
            # full hit.  The consumer enters at pos = L-1 and its first
            # step RE-WRITES that position's K/V — when the cached
            # pages cover all L tokens that write would land in the
            # last shared page, so it gets an eager COW copy; when they
            # cover L-1 the write lands in the first owned page.
            copy = m * PG == L
            keep = m - 1 if copy else m
            # protect the WHOLE matched chain (incl. the COW source):
            # evicting the source here could hand its page to a later
            # plan in the same wave before the copy dispatch reads it
            owned = self._alloc_pages(need - keep, shared[:m])
            if owned is None:
                return None
            for p in shared[:keep]:
                self._pages.incref(p)
            self._slot_pages[slot] = list(shared[:keep]) + owned
            return {"mode": "hit", "req": req, "slot": slot,
                    "shared": keep,
                    "src": shared[m - 1] if copy else -1,
                    "dst": owned[0] if copy else -1}
        if m == 0 and L <= self.prefill_buckets[-1]:
            owned = self._alloc_pages(need)
            if owned is None:
                return None
            self._slot_pages[slot] = owned
            return {"mode": "admit", "req": req, "slot": slot}
        # chunked prefill: a long prompt streams in over several
        # dispatches; a PARTIAL prefix hit maps its cached pages and
        # streams only the divergent suffix
        owned = self._alloc_pages(need - m, shared)
        if owned is None:
            return None
        for p in shared:
            self._pages.incref(p)
        self._slot_pages[slot] = list(shared) + owned
        if m:
            self._count("prefix_hits")
            telemetry.emit("prefix_cache_hit",
                           server=self.telemetry_label,
                           request_id=req.stream.request_id,
                           shared_pages=m, cow_copy=False,
                           partial=True)
        return {"mode": "chunk", "req": req, "slot": slot,
                "off": m * PG, "zero": owned}

    def _page_table(self):
        """The step's ``(S, MAXP)`` int32 page-table operand, sentinel-
        padded.  Slots mid-chunked-prefill get ALL-SENTINEL rows: their
        reserved pages are being filled by chunk dispatches, and the
        step's masked zombie lane must not scribble on them — the real
        row appears once the final chunk activates the slot."""
        progs = self._progs
        pt = onp.full((len(self._slots), progs.maxp), progs.num_pages,
                      onp.int32)
        for i, row in enumerate(self._slot_pages):
            if row and i not in self._chunk_slots:
                pt[i, :len(row)] = row
        return pt

    def _dispatch_hits(self, hits):
        """ONE masked dispatch admits a whole wave of prefix-cache
        HITS: the shared pages are already resident, so the executable
        only COW-copies each row's boundary page (if any) and scatters
        slot state — no model forward, zero prefill dispatches, and the
        request's first token arrives from the NEXT regular step
        (TTFT ≈ one decode step)."""
        A = _bucket_for(self.admit_sizes, len(hits))
        fn = self._progs.admit_hit_fn(A)
        self._watch_dispatch(fn)
        sentinel = self._progs.num_pages
        meta = onp.zeros((A, schema.meta_width("hit")), onp.int32)
        meta[:, schema.meta_col("hit", "true_len")] = 1
        dls = onp.full((A,), onp.inf, onp.float32)
        srcs = onp.full((A,), sentinel, onp.int32)
        dsts = onp.full((A,), sentinel, onp.int32)
        # int8 recycled-page reset operand: each hit row's freshly
        # OWNED pages (decode frontier + the COW dst) — the shared
        # prefix pages keep their resident scales.  The executable
        # zeroes these AFTER its src gathers, BEFORE its dst scatter.
        zpages = onp.full((A, self._progs.maxp), sentinel, onp.int32)
        now = time.perf_counter()
        S = len(self._slots)
        busy = sum(r is not None for r in self._slots)
        occ = busy / S if S else 0.0
        for i, plan in enumerate(hits):
            slot, req = plan["slot"], plan["req"]
            L = req.prompt.size
            meta[i] = schema.meta_row(
                "hit", valid=1, true_len=L, slot=slot,
                stop_pos=L + req.max_new - 1, seed=req.seed,
                last_tok=int(req.prompt[-1]),
                spec_depth=self._slot_spec_depth(req))
            if req.deadline is not None:
                dls[i] = req.deadline - self._epoch
            if plan["src"] >= 0:
                srcs[i] = plan["src"]
                dsts[i] = plan["dst"]
                self._count("cow_copies")
            fresh = self._slot_pages[slot][plan["shared"]:]
            zpages[i, :len(fresh)] = fresh
            self._count("prefix_hits")
            wait = now - req.stream.submit_time
            req.span.update(queue_wait_s=wait, wave=len(hits),
                            a_bucket=A, p_bucket=0,
                            occupancy_at_admit=occ)
            self._tele["wait"].observe(wait)
            telemetry.emit("prefix_cache_hit",
                           server=self.telemetry_label,
                           request_id=req.stream.request_id,
                           shared_pages=plan["shared"],
                           cow_copy=plan["src"] >= 0, partial=False)
        with telemetry.annotation("mx:serve:admit_hit"):
            new_state = fn(meta, dls, srcs, dsts, zpages,
                           *self._state)
        self._state = new_state
        if self._torn:
            self._state = None

    def _pump_chunks(self):
        """Advance every mid-prefill request by ONE chunk dispatch per
        pump, interleaved with decode steps so resident sequences keep
        streaming while a long prompt fills in.  Returns ``(worked,
        may_retire)`` — the latter when a final chunk could retire its
        request inside the dispatch (1-token budget / EOS-at-admit)."""
        worked = may_retire = False
        for rec in list(self._chunking):
            req, slot = rec["req"], rec["slot"]
            if req.cancelled or (req.deadline is not None
                                 and self._clock() >= req.deadline):
                self._drop_chunk_record(slot)
                with self._lock:
                    if self._slots[slot] is req:
                        self._slots[slot] = None
                self._free_slot_pages(slot)
                self._retire_aside(
                    req, "cancelled" if req.cancelled
                    else "deadline_exceeded")
                worked = True
                continue
            final = self._dispatch_chunk(rec)
            worked = True
            if final:
                self._drop_chunk_record(slot)
                may_retire |= req.max_new == 1
        return worked, may_retire

    def _dispatch_chunk(self, rec):
        """ONE slice of a streaming prefill: up to the largest pinned
        prompt bucket of tokens runs through the slot's page-table row
        at the record's landing offset.  The FINAL chunk also samples
        the request's first token and activates the slot — its
        readback routes through the admit drain path.  Returns whether
        this was the final chunk."""
        req, slot, off = rec["req"], rec["slot"], rec["off"]
        fault_point("serve.chunk", server=self.telemetry_label)
        L = int(req.prompt.size)
        remaining = L - off
        top = self.prefill_buckets[-1]
        if remaining > top:
            C, final, ntok = top, False, top
        else:
            C = _bucket_for(self.prefill_buckets, remaining)
            final, ntok = True, remaining
        fn = self._progs.chunk_fn(C)
        self._watch_dispatch(fn)
        toks = onp.zeros((C,), onp.int32)
        toks[:ntok] = req.prompt[off:off + ntok]
        meta = onp.asarray(schema.meta_row(
            "chunk", final=1 if final else 0, slot=slot, true_len=L,
            stop_pos=L + req.max_new - 1, seed=req.seed,
            nlast=(L - 1 - off) if final else C - 1, off=off,
            spec_depth=self._slot_spec_depth(req)), onp.int32)
        dl = onp.float32(onp.inf if req.deadline is None
                         else req.deadline - self._epoch)
        ptrow = onp.full((self._progs.maxp,), self._progs.num_pages,
                         onp.int32)
        row = self._slot_pages[slot]
        ptrow[:len(row)] = row
        # int8 recycled-page reset operand: the slot's freshly
        # allocated pages ride the FIRST chunk dispatch only (their
        # stale scales must be zeroed before the first RMW floors on
        # them); later chunks send all-sentinel — they must keep the
        # scale ratchet of earlier chunks.  f32 pools ignore it.
        zrow = onp.full((self._progs.maxp,), self._progs.num_pages,
                        onp.int32)
        zero = rec.pop("zero", None)
        if zero:
            zrow[:len(zero)] = zero
        param_vals, q8, sw = self._progs.operands
        with telemetry.annotation("mx:serve:chunk"):
            new_state, (first, done) = fn(param_vals, q8, sw, toks,
                                          meta, dl, ptrow, zrow,
                                          *self._state)
        self._state = new_state
        if self._torn:
            self._state = None
            return True
        self._count("chunk_dispatches")
        rec["off"] = off + ntok
        telemetry.emit("serve_chunk", server=self.telemetry_label,
                       request_id=req.stream.request_id, slot=slot,
                       c_bucket=C, offset=off, final=final)
        if final:
            wait = time.perf_counter() - req.stream.submit_time
            req.span.update(queue_wait_s=wait, wave=1, a_bucket=1,
                            p_bucket=C)
            self._tele["wait"].observe(wait)
            if self._prefix is not None:
                self._prefix.register(req.prompt, L,
                                      self._slot_pages[slot])
            self._inflight.append(("admit", (first, done),
                                   [(slot, req)]))
        return final

    # speculative decoding -------------------------------------------------- #
    def _build_drafts(self):
        """Host-side draft proposals for this pump, ``{slot: 1-D int32
        drafts}``; ``None`` when no slot proposed anything (the pump
        takes a plain step, costing exactly what it costs with
        speculation off).  Drafts chain off the last token ROUTED to
        each stream, so a just-admitted slot — including a prefix-
        cache hit, whose first step RECOMPUTES the final prompt
        position (ISSUE 16) — proposes nothing until its first step
        drains: the speculation ramp-in the COW semantics require
        falls out of the drain ordering for free."""
        drafts = {}
        for slot, req in enumerate(self._slots):
            if req is None or req.cancelled \
                    or slot in self._chunk_slots:
                continue
            toks = req.stream._toks
            if not toks:
                continue   # no routed token to chain from yet
            # the verify block emits up to k + 1 tokens; never draft
            # past the request's remaining budget (the device clamps
            # too — this just avoids wasted columns)
            k = min(self.spec_depth, req.max_new - len(toks) - 1)
            if k < 1:
                continue
            hist = onp.concatenate(
                [req.prompt, onp.asarray(toks, onp.int32)])
            prop = self._drafter.propose(hist, k)
            if prop is not None and len(prop):
                drafts[slot] = onp.asarray(
                    prop, onp.int32).reshape(-1)[:k]
        return drafts or None

    def _dispatch_verify(self, drafts):
        """ONE bucketed ``(S, k)`` draft-and-verify dispatch for this
        pump's proposals (k = smallest pinned spec bucket that fits
        the longest draft): column 0 replays each slot's device-held
        last token — a plain step for slots that proposed nothing —
        and the executable accepts each slot's longest matching
        prefix device-side.  Accepted K/V columns are already in the
        paged pool; rejected tails need no undo (pages were reserved
        all-or-nothing at admission, so rollback is the device-side
        position simply not advancing — never a copy, never a
        refcount; docs/SERVING.md)."""
        fault_point("serve.verify", server=self.telemetry_label)
        k = _bucket_for(self.spec_sizes,
                        max(d.size for d in drafts.values()))
        fn = self._progs.verify_fn(k)
        self._watch_dispatch(fn)
        S = len(self._slots)
        block = onp.zeros((S, k), onp.int32)
        nd = onp.zeros((S,), onp.int32)
        for slot, d in drafts.items():
            nd[slot] = d.size
            block[slot, :d.size] = d
        param_vals, q8, sw = self._progs.operands
        now = onp.float32(self._clock() - self._epoch)
        with telemetry.annotation("mx:serve:verify"):
            new_state, out = fn(param_vals, q8, sw, now,
                                self._page_table(), block, nd,
                                *self._state)
        self._state = new_state
        if self._torn:
            self._state = None
            return
        self._count("verify_dispatches")
        busy = sum(r is not None for r in self._slots)
        self._occupied_lane_steps += busy
        self._capacity_lane_steps += S
        self._tele["occ"].set(busy / S)
        self._tele["pages"].set(self._pages.in_use)
        self._inflight.append(("verify", out,
                               (list(self._slots), nd, k)))

    # the step ------------------------------------------------------------ #
    def _dispatch_step(self):
        fault_point("serve.step", server=self.telemetry_label)
        self._watch_dispatch(self._progs.step_fn())
        param_vals, q8, sw = self._progs.operands
        # the step's wall clock: a float32 OPERAND (same aval every
        # call — never a retrace), against which the executable checks
        # every slot's deadline
        now = onp.float32(self._clock() - self._epoch)
        with telemetry.annotation("mx:serve:step"):
            new_state, out = self._progs.step_fn()(
                param_vals, q8, sw, now, self._page_table(),
                *self._state)
        self._state = new_state
        if self._torn:
            # late completion of a wedged dispatch after watchdog
            # teardown: don't re-pin the released pool (the gauge and
            # stats() already report 0 bytes)
            self._state = None
            return
        self._count("step_dispatches")
        self._steps += 1
        busy = sum(r is not None for r in self._slots)
        self._occupied_lane_steps += busy
        self._capacity_lane_steps += len(self._slots)
        self._tele["occ"].set(busy / len(self._slots))
        self._tele["pages"].set(self._pages.in_use)
        self._inflight.append(("step", out, list(self._slots)))

    # drain ---------------------------------------------------------------- #
    def _drain_admits(self):
        """Route every in-flight ADMIT readback (selective drain is
        stream-order-safe: an admit is always a request's first entry,
        and step entries only touch other, older requests)."""
        rest = deque()
        while self._inflight:
            kind, arrays, meta = self._inflight.popleft()
            if kind != "admit":
                rest.append((kind, arrays, meta))
                continue
            self._route_admit(arrays, meta)
        self._inflight = rest

    def _route_admit(self, arrays, wave):
        """Route one admission wave's ``(first_tok, done)`` readback to
        its requests' streams, in wave order — which IS submission
        order, so per-request stream order is preserved.  (A final
        CHUNK's scalar readback rides this path too, as a wave of
        one — hence the flatten.)"""
        first = onp.asarray(arrays[0]).reshape(-1)
        done = onp.asarray(arrays[1]).reshape(-1)
        for i, (slot, req) in enumerate(wave):
            if req.cancelled:
                continue   # retired aside; the lane's output is void
            tok = int(first[i])
            req.stream._push(tok)
            if done[i]:
                req.stream._finish()
                self._observe_retire(req,
                                     self._retire_reason(req, tok))
                freed = False
                with self._lock:
                    if self._slots[slot] is req:
                        self._slots[slot] = None
                        freed = True
                if freed:
                    self._free_slot_pages(slot)

    def _flush_drain(self, keep=0, final=False):
        """Route in-flight dispatches' readback arrays to their streams
        and free retired slots, oldest-first (the device stream is
        FIFO, so only the newest entries can still be computing).
        ``keep`` leaves that many newest entries in flight — the
        host/device overlap while the loop is actively stepping."""
        if final:
            keep = 0
        worked = False
        while len(self._inflight) > keep:
            kind, arrays, meta = self._inflight.popleft()
            worked = True
            if kind == "admit":
                self._route_admit(arrays, meta)
            elif kind == "verify":
                self._route_verify(arrays, meta)
            else:
                toks, emitted, done = (onp.asarray(a) for a in arrays)
                snapshot = meta
                for slot, req in enumerate(snapshot):
                    if req is None or req.cancelled \
                            or not emitted[slot]:
                        continue
                    tok = int(toks[slot])
                    req.stream._push(tok)
                    if done[slot]:
                        req.stream._finish()
                        self._observe_retire(
                            req, self._retire_reason(req, tok))
                        freed = False
                        with self._lock:
                            if self._slots[slot] is req:
                                self._slots[slot] = None
                                freed = True
                        if freed:
                            self._free_slot_pages(slot)
        return worked

    def _route_verify(self, arrays, meta):
        """Route one verify dispatch's ``(tokens (S, K), advance (S,),
        done (S,))`` readback: every live lane emits its accepted
        prefix plus the executable's own next token (``advance``
        tokens, >= 1 — a slot that proposed nothing gets its plain-
        step token through column 0), and the draft ledgers advance by
        exactly what each surviving stream's proposals resolved to, so
        accepted + rejected == proposed holds per stream, per server
        and in the recording (``telemetry_report --check-serve``
        re-derives it)."""
        toks, adv, done = (onp.asarray(a) for a in arrays)
        snapshot, nd, k_bucket = meta
        proposed_t = accepted_t = rejected_t = 0
        for slot, req in enumerate(snapshot):
            if req is None or req.cancelled:
                continue
            n = int(adv[slot])
            if n < 1:
                continue   # masked lane (inactive this dispatch)
            for t in toks[slot, :n]:
                req.stream._push(int(t))
            proposed = int(nd[slot])
            if proposed:
                accepted = n - 1
                rejected = proposed - accepted
                req.stream.draft_accepted += accepted
                req.stream.draft_rejected += rejected
                proposed_t += proposed
                accepted_t += accepted
                rejected_t += rejected
            if done[slot]:
                req.stream._finish()
                self._observe_retire(
                    req,
                    self._retire_reason(req, int(toks[slot, n - 1])))
                freed = False
                with self._lock:
                    if self._slots[slot] is req:
                        self._slots[slot] = None
                        freed = True
                if freed:
                    self._free_slot_pages(slot)
        if proposed_t:
            self._count("draft_proposed", proposed_t)
            self._count("draft_accepted", accepted_t)
            self._count("draft_rejected", rejected_t)
        telemetry.emit("serve_spec", server=self.telemetry_label,
                       k_bucket=k_bucket, proposed=proposed_t,
                       accepted=accepted_t, rejected=rejected_t)

    # request-span telemetry ------------------------------------------------ #
    def _retire_reason(self, req, last_tok):
        """The step/admit executables fold EOS, budget exhaustion and
        deadline expiry into one ``done`` flag; the host recovers
        which fired from the final token and the emitted count (EOS
        wins when several land on the same token; a full budget is
        ``max_len`` whether or not a deadline was also set)."""
        if self.eos_id is not None and last_tok == self.eos_id:
            return "eos"
        if len(req.stream._toks) >= req.max_new:
            return "max_len"
        if req.deadline is not None:
            return "deadline_exceeded"
        return "max_len"

    def _observe_retire(self, req, reason):
        """Close a request's span: registry observations (TTFT,
        inter-token gaps, requests-by-reason) + one ``serve_request``
        event, plus the dedicated failure-cause events
        (``deadline_exceeded`` / ``request_cancelled``) the failure
        report aggregates.  Runs at retirement only — never per token,
        never under ``_lock`` — and exactly once per request (the
        ``retired`` flag guards the cancel-vs-drain and
        teardown-after-failure races)."""
        if req.retired:
            return
        req.retired = True
        if reason == "deadline_exceeded":
            telemetry.emit("deadline_exceeded",
                           server=self.telemetry_label,
                           request_id=req.stream.request_id,
                           tokens=len(req.stream._toks),
                           max_new=req.max_new)
        elif reason == "cancelled":
            telemetry.emit("request_cancelled",
                           server=self.telemetry_label,
                           request_id=req.stream.request_id,
                           tokens=len(req.stream._toks))
        st = req.stream
        sp = req.span
        ttft = st.ttft
        if ttft is not None:
            self._tele["ttft"].observe(ttft)
        gap = self._tele["gap"]
        times = st.times
        for a, b in zip(times, times[1:]):
            gap.observe(b - a)
        telemetry.counter("serve_requests_total",
                          server=self.telemetry_label,
                          reason=reason).inc()
        telemetry.emit(
            "serve_request", server=self.telemetry_label,
            request_id=st.request_id, reason=reason,
            tokens=len(times),
            ttft_s=None if ttft is None else round(ttft, 6),
            queue_wait_s=None if "queue_wait_s" not in sp
            else round(sp["queue_wait_s"], 6),
            wave=sp.get("wave"), a_bucket=sp.get("a_bucket"),
            p_bucket=sp.get("p_bucket"),
            occupancy_at_admit=sp.get("occupancy_at_admit"),
            draft_accepted=st.draft_accepted,
            draft_rejected=st.draft_rejected)

    # sync fallback -------------------------------------------------------- #
    def _pump_sync(self):
        from ..models.decoding import kv_generate

        req = self._take_pending()
        if req is None:
            return False
        if req.cancelled:
            self._retire_aside(req, "cancelled")
            return True
        if req.deadline is not None and self._clock() >= req.deadline:
            # queue-lapsed deadline; the sync fallback cannot retire
            # MID-generation (no step boundaries), so this pre-check
            # is the whole deadline story here (docs/SERVING.md)
            self._retire_aside(req, "deadline_exceeded")
            return True
        self._count("sync_requests")
        wait = time.perf_counter() - req.stream.submit_time
        req.span["queue_wait_s"] = wait
        self._tele["wait"].observe(wait)
        try:
            out = kv_generate(self.model, req.prompt[None],
                              max_new_tokens=req.max_new,
                              temperature=self.temperature,
                              top_k=self.top_k, seed=req.seed,
                              weights=self.weights)
            new = out[0, req.prompt.size:]
            last = None
            if self.eos_id is not None:
                for t in new:
                    last = int(t)
                    req.stream._push(last)
                    if last == self.eos_id:
                        break
                req.stream._finish()
            else:
                for t in new:
                    last = int(t)
                    req.stream._push(last)
                req.stream._finish()
            self._observe_retire(
                req, "max_len" if last is None
                else self._retire_reason(req, last))
        except Exception as e:                 # surface, don't hang
            req.stream._finish(e)
            self._observe_retire(req, "error")
        return True
