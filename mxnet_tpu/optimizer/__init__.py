"""Optimizers + LR schedulers (reference: ``python/mxnet/optimizer/``)."""
from .optimizer import (Optimizer, SGD, NAG, Adam, AdamW, Nadam, LAMB, LARS,
                        RMSProp, AdaGrad, AdaDelta, Ftrl, FTML, Signum, SGLD,
                        register, create)
from . import lr_scheduler
from .lr_scheduler import (LRScheduler, FactorScheduler,
                           MultiFactorScheduler, PolyScheduler,
                           CosineScheduler)
