"""Optimizers.

Reference surface: ``python/mxnet/optimizer/optimizer.py`` (SURVEY.md §3.2
"Optimizers": registry @register; SGD/NAG/Adam/AdamW/LAMB/LARS/RMSProp/
Adagrad/Adadelta/Ftrl/FTML/Signum/SGLD; multi-precision via mp_* ops; fused
aggregated updates; anchor ``update_multi_precision``).

TPU-native redesign: every optimizer defines ONE pure jax update rule
``_update_rule(weight, grad, state, lr, wd) -> (new_weight, new_state)``.
The imperative ``update(index, weight, grad, state)`` surface matches the
reference; the same rule is consumed by the fully-jitted train step
(Trainer/fit path) so the whole optimizer fuses into the backward XLA
program — the analog of the reference's fused ``multi_sgd_mom_update``
kernels, supplied by XLA fusion instead of hand-written CUDA.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = [
    "Optimizer", "SGD", "NAG", "Adam", "AdamW", "Nadam", "LAMB", "LARS",
    "RMSProp", "AdaGrad", "AdaDelta", "Ftrl", "FTML", "Signum", "SGLD",
    "register", "create", "apply_counters", "reset_apply_counters",
    "fused_enabled",
]

# Dispatch accounting for the fused multi-tensor apply (read by the
# dispatch-count regression test and benchmark/step_breakdown.py):
#   fused_calls      — jitted group-apply invocations (one per group/step)
#   fused_params     — parameters served by those calls
#   fallback_params  — parameters that took the legacy per-param path
apply_counters = {"fused_calls": 0, "fused_params": 0, "fallback_params": 0}


def reset_apply_counters():
    for k in apply_counters:
        apply_counters[k] = 0


def fused_enabled() -> bool:
    """Escape hatch: ``MXNET_FUSED_OPTIMIZER=0`` restores the legacy
    per-parameter update loop (read per call so tests can toggle it)."""
    return os.environ.get("MXNET_FUSED_OPTIMIZER", "1") != "0"

_REGISTRY: dict = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    if name.lower() not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name}")
    return _REGISTRY[name.lower()](**kwargs)


def _as_jax(x):
    return x._data if isinstance(x, NDArray) else x


def _cast_like(ref, new):
    """Cast every array leaf of ``new`` back to the dtype of the matching
    leaf in ``ref`` — keeps the optimizer-state dtype signature stable
    across steps so one compiled executable (with donated state buffers)
    serves every step (the same bf16 dtype-preservation discipline
    ``SPMDTrainer._make_step_fn`` applies)."""
    return jax.tree.map(
        lambda a, b: b.astype(a.dtype)
        if hasattr(a, "dtype") and hasattr(b, "astype") else b, ref, new)


class Optimizer:
    """Base optimizer (reference anchor ``class Optimizer``)."""

    # SGLD draws host-side RNG inside its rule; a traced-once executable
    # would replay the same noise every step, so it opts out of fusion.
    _fusable = True

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0,
                 aggregate_num=None, use_fused_step=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: dict = {}
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = dict(param_dict or {})
        self.lr_mult: dict = {}
        self.wd_mult: dict = {}
        self.aggregate_num = aggregate_num

    # -- lr/wd plumbing ---------------------------------------------------- #
    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.set_learning_rate(lr)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is "
                             "active")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _get_lr(self, index):
        lr = self.learning_rate
        if index in self.param_dict:
            p = self.param_dict[index]
            lr *= p.lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _update_count(self, index):
        self._index_update_count.setdefault(index, self.begin_num_update)
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    # -- state ------------------------------------------------------------- #
    def create_state(self, index, weight):
        """Return the pytree of state arrays for one parameter (pure)."""
        return None

    def create_state_multi_precision(self, index, weight):
        w = _as_jax(weight)
        if self.multi_precision and w.dtype in (jnp.float16, jnp.bfloat16):
            master = w.astype(jnp.float32)
            return (master, self.create_state(index, NDArray(master)))
        return self.create_state(index, weight)

    # -- update ------------------------------------------------------------ #
    def _preprocess_grad(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _update_rule(self, weight, grad, state, lr, wd, t):
        """Pure: (w, g, state, lr, wd, step) -> (new_w, new_state)."""
        raise NotImplementedError

    def update(self, index, weight, grad, state):
        """Imperative in-place update of one parameter (reference
        ``Optimizer.update``).  Accepts lists for the fused multi-tensor
        surface."""
        if isinstance(index, (list, tuple)):
            return self.multi_update(index, weight, grad, state)
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        w = _as_jax(weight)
        g = self._preprocess_grad(_as_jax(grad).astype(w.dtype))
        new_w, new_state = self._update_rule(w, g, state, lr, wd, t)
        weight._rebind(new_w)
        return new_state

    def _use_mp(self, w, state):
        """True when the multi-precision (fp32-master) path is active for
        this (weight, state) pair — the single definition shared by the
        per-param and fused apply paths."""
        return (self.multi_precision
                and w.dtype in (jnp.float16, jnp.bfloat16)
                and isinstance(state, tuple) and len(state) == 2
                and getattr(state[0], "dtype", None) == jnp.float32)

    def update_multi_precision(self, index, weight, grad, state):
        """fp16/bf16 weights with fp32 master copy (reference anchor
        ``update_multi_precision`` / ``mp_*`` ops)."""
        if isinstance(index, (list, tuple)):
            return self.multi_update(index, weight, grad, state)
        w = _as_jax(weight)
        use_mp = self._use_mp(w, state)
        if not use_mp:
            return self.update(index, weight, grad, state)
        master, inner = state
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        g = self._preprocess_grad(_as_jax(grad).astype(jnp.float32))
        new_master, new_inner = self._update_rule(master, g, inner, lr, wd, t)
        weight._rebind(new_master.astype(w.dtype))
        return (new_master, new_inner)

    # -- fused multi-tensor apply ------------------------------------------ #
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_fused_cache", None)  # jitted executables don't pickle
        return state

    def _hyper_key(self):
        """Scalar hyperparameters the update rules close over (momentum,
        betas, epsilons, ...) — part of the executable cache key so
        mutating one retraces instead of replaying a stale closure.
        Per-step quantities (lr, wd, rescale, clip, step counts) are
        traced operands and excluded."""
        skip = {"rescale_grad", "num_update", "begin_num_update", "lr",
                "wd", "clip_gradient", "aggregate_num"}
        return tuple(sorted(
            (k, v) for k, v in self.__dict__.items()
            if k not in skip and isinstance(v, (bool, int, float, str))))

    def _apply_one(self, w, g, s, lr, wd, t, rescale, clip, use_mp,
                   has_clip):
        """Pure single-parameter apply with the fused dtype discipline —
        the ONE implementation behind both the jitted group apply
        (``_build_fused_apply``) and the whole-step executable
        (``fused_step_apply``), so the two paths cannot drift."""
        if use_mp:
            master, inner = s
            g2 = g.astype(jnp.float32) * rescale
            if has_clip:
                g2 = jnp.clip(g2, -clip, clip)
            nm, ni = self._update_rule(master, g2, inner, lr, wd, t)
            return nm.astype(w.dtype), (nm, _cast_like(inner, ni))
        # match the legacy per-param dtype discipline: grad is cast to
        # the weight dtype BEFORE rescale/clip, and the new weight is
        # rounded back (the traced f32 lr/wd scalars promote
        # low-precision math to f32 — more accurate than the legacy
        # loop, within 1 ulp of it)
        g2 = g.astype(w.dtype) * rescale.astype(w.dtype)
        if has_clip:
            cl = clip.astype(w.dtype)
            g2 = jnp.clip(g2, -cl, cl)
        nw, ns = self._update_rule(w, g2, s, lr, wd, t)
        return nw.astype(w.dtype), _cast_like(s, ns)

    def _build_fused_apply(self, use_mp, has_clip):
        """One pure pytree-level apply for a parameter group, jitted with
        weight/state buffer donation so the update is in-place at the XLA
        level.  ``lrs``/``wds``/``ts`` are stacked per-param scalars and
        ``rescale``/``clip`` traced scalars, so ONE compiled executable
        serves every step of training."""

        def apply_fn(ws, gs, ss, lrs, wds, ts, rescale, clip):
            new_ws, new_ss = [], []
            for i, (w, g, s) in enumerate(zip(ws, gs, ss)):
                nw, ns = self._apply_one(w, g, s, lrs[i], wds[i], ts[i],
                                         rescale, clip, use_mp, has_clip)
                new_ws.append(nw)
                new_ss.append(ns)
            return new_ws, new_ss

        return jax.jit(apply_fn, donate_argnums=(0, 2))

    def fused_step_apply(self, ws, gs, ss, mp_flags, lrs, wds, ts, rescale):
        """Pure (trace-safe) multi-tensor apply for use INSIDE a larger
        jitted step — the fused train step (``gluon/fused_step.py``)
        traces this directly so forward+backward+apply compile into ONE
        executable; donation belongs to that enclosing executable, not
        here.  ``mp_flags`` are per-parameter (a mixed bf16+master / f32
        model applies in one pass instead of one call per group);
        ``rescale`` is the traced scalar that carries the gradient-
        accumulation 1/(N·batch) factor.  ``clip_gradient`` is read at
        trace time (hyperparameter, part of the step's cache key)."""
        has_clip = self.clip_gradient is not None
        clip = jnp.float32(self.clip_gradient if has_clip else 0.0)
        new_ws, new_ss = [], []
        for i, (w, g, s, mp) in enumerate(zip(ws, gs, ss, mp_flags)):
            nw, ns = self._apply_one(w, g, s, lrs[i], wds[i], ts[i],
                                     rescale, clip, mp, has_clip)
            new_ws.append(nw)
            new_ss.append(ns)
        return new_ws, new_ss

    def multi_update(self, indices, weights, grads, states):
        """Fused multi-tensor apply (the reference's ``multi_sgd_update``
        / ``MXNET_OPTIMIZER_AGGREGATION_SIZE`` aggregation): groups the
        parameters by (multi-precision flag, dtype, sharding) and applies
        each group in ONE jitted XLA call with donated weight/state
        buffers, so a ``Trainer.step`` issues O(#groups) dispatches
        instead of O(#params).

        Weights are updated in place (rebound); returns the new states
        aligned with ``indices``.  Sparse grads, non-fusable optimizers
        (SGLD), and ``MXNET_FUSED_OPTIMIZER=0`` fall back to the legacy
        per-param path — numerics there are bit-identical to before.
        """
        n = len(indices)
        new_states: list = [None] * n
        fuse = fused_enabled() and self._fusable
        groups: dict = {}
        fallback = []
        for pos in range(n):
            w, g = weights[pos], grads[pos]
            if not fuse or getattr(g, "_sparse_kind", False) \
                    or getattr(w, "_sparse_kind", False):
                fallback.append(pos)
                continue
            wj = _as_jax(w)
            use_mp = self._use_mp(wj, states[pos])
            try:
                shard = str(wj.sharding)
            except Exception:  # non-jax leaves (plain numpy in tests)
                shard = None
            groups.setdefault((use_mp, str(wj.dtype), shard),
                              []).append(pos)
        for pos in fallback:
            new_states[pos] = self.update_multi_precision(
                indices[pos], weights[pos], grads[pos], states[pos])
            apply_counters["fallback_params"] += 1
        if not groups:
            return new_states
        has_clip = self.clip_gradient is not None
        clip = jnp.float32(self.clip_gradient if has_clip else 0.0)
        rescale = jnp.float32(self.rescale_grad)
        cache = self.__dict__.setdefault("_fused_cache", {})
        agg = self.aggregate_num if self.aggregate_num else None
        for (use_mp, _dt, _sh), poss in groups.items():
            key = (use_mp, has_clip, self._hyper_key())
            fn = cache.get(key)
            if fn is None:
                fn = self._build_fused_apply(use_mp, has_clip)
                cache[key] = fn
            chunks = [poss[i:i + agg] for i in range(0, len(poss), agg)] \
                if agg else [poss]
            for chunk in chunks:
                lrs, wds, ts = [], [], []
                for pos in chunk:
                    idx = indices[pos]
                    self._update_count(idx)
                    lrs.append(self._get_lr(idx))
                    wds.append(self._get_wd(idx))
                    ts.append(self._index_update_count[idx])
                new_ws, new_ss = fn(
                    [_as_jax(weights[pos]) for pos in chunk],
                    [_as_jax(grads[pos]) for pos in chunk],
                    [states[pos] for pos in chunk],
                    jnp.asarray(lrs, jnp.float32),
                    jnp.asarray(wds, jnp.float32),
                    jnp.asarray(ts, jnp.int32),
                    rescale, clip)
                apply_counters["fused_calls"] += 1
                apply_counters["fused_params"] += len(chunk)
                for pos, nw, ns in zip(chunk, new_ws, new_ss):
                    weights[pos]._rebind(nw)
                    new_states[pos] = ns
        return new_states


@register
class SGD(Optimizer):
    """SGD with momentum (reference anchors ``sgd_update`` /
    ``sgd_mom_update``)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        w = _as_jax(weight)
        return jnp.zeros_like(w)

    def _update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.momentum == 0.0:
            return w - lr * g, None
        mom = state * self.momentum - lr * g
        return w + mom, mom


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference anchor ``nag_mom_update``)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, momentum=momentum,
                         **kwargs)

    def _update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.momentum == 0.0:
            return w - lr * g, None
        mom = state * self.momentum + g
        return w - lr * (g + self.momentum * mom), mom


@register
class Adam(Optimizer):
    """Reference anchor ``adam_update``."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))  # mean, var

    def _update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        coef1 = 1 - self.beta1 ** t
        coef2 = 1 - self.beta2 ** t
        lr_t = lr * jnp.sqrt(coef2) / coef1
        return w - lr_t * m / (jnp.sqrt(v) + self.epsilon), (m, v)


@register
class AdamW(Adam):
    """Decoupled weight decay (reference contrib ``adamw_update``)."""

    def _update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        coef1 = 1 - self.beta1 ** t
        coef2 = 1 - self.beta2 ** t
        lr_t = lr * jnp.sqrt(coef2) / coef1
        return w - lr_t * (m / (jnp.sqrt(v) + self.epsilon) + wd * w), (m, v)


@register
class Nadam(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kwargs)
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        w = _as_jax(weight)
        # the momentum-schedule product is per-state (not on self) so the
        # rule stays pure and jit-safe under SPMDTrainer
        return (jnp.zeros_like(w), jnp.zeros_like(w),
                jnp.ones((), jnp.float32))

    def _update_rule(self, w, g, state, lr, wd, t):
        m, v, m_sched = state
        g = g + wd * w
        momentum_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t1 = self.beta1 * (1 - 0.5 * 0.96 **
                                    ((t + 1) * self.schedule_decay))
        m_sched = m_sched * momentum_t
        m_schedule_next = m_sched * momentum_t1
        g_prime = g / (1 - m_sched)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        m_prime = m / (1 - m_schedule_next)
        v_prime = v / (1 - self.beta2 ** t)
        m_bar = (1 - momentum_t) * g_prime + momentum_t1 * m_prime
        return (w - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon),
                (m, v, m_sched))


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (reference anchors
    ``lamb_update_phase1/2``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def _update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        update = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w
        wnorm = jnp.linalg.norm(w)
        unorm = jnp.linalg.norm(update)
        if self.lower_bound is not None:
            wnorm = jnp.maximum(wnorm, self.lower_bound)
        if self.upper_bound is not None:
            wnorm = jnp.minimum(wnorm, self.upper_bound)
        trust = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
        return w - lr * trust * update, (m, v)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference ``LARS`` optimizer)."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return jnp.zeros_like(_as_jax(weight))

    def _update_rule(self, w, g, state, lr, wd, t):
        wnorm = jnp.linalg.norm(w)
        gnorm = jnp.linalg.norm(g)
        trust = jnp.where(
            (wnorm > 0) & (gnorm > 0),
            self.eta * wnorm / (gnorm + wd * wnorm + self.epsilon), 1.0)
        g = g + wd * w
        mom = self.momentum * state + lr * trust * g
        return w - mom, mom


@register
class RMSProp(Optimizer):
    """Reference anchor ``rmsprop_update`` (centered variant =
    ``rmspropalex_update``)."""

    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho = rho
        self.momentum = momentum
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        w = _as_jax(weight)
        if self.centered:
            return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros_like(w))
        return (jnp.zeros_like(w),)

    def _update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if not self.centered:
            (n,) = state
            n = self.rho * n + (1 - self.rho) * jnp.square(g)
            new_w = w - lr * g / jnp.sqrt(n + self.epsilon)
            new_state = (n,)
        else:
            n, mg, delta = state
            n = self.rho * n + (1 - self.rho) * jnp.square(g)
            mg = self.rho * mg + (1 - self.rho) * g
            delta = self.momentum * delta - \
                lr * g / jnp.sqrt(n - jnp.square(mg) + self.epsilon)
            new_w = w + delta
            new_state = (n, mg, delta)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return new_w, new_state


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return jnp.zeros_like(_as_jax(weight))

    def _update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        hist = state + jnp.square(g)
        return w - lr * g / (jnp.sqrt(hist) + self.epsilon), hist


@register
class AdaDelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def _update_rule(self, w, g, state, lr, wd, t):
        acc_g, acc_delta = state
        g = g + wd * w
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta + self.epsilon) / \
            jnp.sqrt(acc_g + self.epsilon) * g
        acc_delta = self.rho * acc_delta + (1 - self.rho) * jnp.square(delta)
        return w - lr * delta, (acc_g, acc_delta)


@register
class Ftrl(Optimizer):
    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))  # z, n

    def _update_rule(self, w, g, state, lr, wd, t):
        z, n = state
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + jnp.square(g)
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) /
            ((self.beta + jnp.sqrt(n)) / lr + wd), 0.0)
        return new_w.astype(w.dtype), (z, n)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros_like(w))

    def _update_rule(self, w, g, state, lr, wd, t):
        d, v, z = state
        g = g + wd * w
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        d_t = (1 - self.beta1 ** t) / lr * \
            (jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * w
        return -z / d_t, (d_t, v, z)


@register
class Signum(Optimizer):
    """Sign-SGD with momentum (reference anchor ``signum_update``)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros_like(_as_jax(weight))

    def _update_rule(self, w, g, state, lr, wd, t):
        if self.momentum == 0.0:
            return w - lr * (jnp.sign(g) + self.wd_lh * w), None
        mom = self.momentum * state - (1 - self.momentum) * (g + wd * w)
        return w - lr * (jnp.sign(-mom) + self.wd_lh * w), mom


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference anchor ``DCASGD``): the
    gradient is corrected with a curvature term λ·g⊙g⊙(w − w_prev) to
    compensate staleness.  On a synchronous TPU step the delay is zero by
    construction, so this matches SGD+momentum — kept for API parity with
    async-PS training scripts."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        w = _as_jax(weight)
        mom = None if self.momentum == 0.0 else jnp.zeros_like(w)
        # previous weight must be a COPY: asarray would alias the live
        # weight buffer, and the fused apply donates both the weight and
        # state operands (double-donating one buffer is an XLA error)
        return (mom, jnp.array(w))  # (momentum, previous weight)

    def _update_rule(self, w, g, state, lr, wd, t):
        mom, prev_w = state
        comp = g + wd * w + self.lamda * g * g * (w - prev_w)
        if mom is None:
            new_w = w - lr * comp
            return new_w, (None, w)
        mom = self.momentum * mom - lr * comp
        new_w = w + mom
        return new_w, (mom, w)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (noise-injected SGD)."""

    # the rule draws a fresh host RNG key per call — tracing it once into
    # a cached executable would replay identical noise every step
    _fusable = False

    def create_state(self, index, weight):
        return None

    def _update_rule(self, w, g, state, lr, wd, t):
        from .. import random as mxrandom
        g = g + wd * w
        noise = jax.random.normal(mxrandom.next_key(), w.shape, w.dtype) * \
            jnp.sqrt(lr)
        return w - 0.5 * lr * g + noise, None


# keep reference aliases
_REGISTRY["adagrad"] = AdaGrad
_REGISTRY["adadelta"] = AdaDelta
