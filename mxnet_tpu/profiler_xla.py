"""Per-XLA-op device profiling — the aggregate table *inside* a fused step.

Reference parity (SURVEY.md §5.1): the reference profiler wraps every
engine ``OprBlock`` execution, so ``MXAggregateProfileStatsPrint`` shows a
per-op totals table.  Under XLA the entire train step is ONE fused program
and host-side hooks see nothing — this module recovers the reference's
visibility by parsing the ``jax.profiler`` device trace: every executed
HLO op's device duration, bytes accessed, and model FLOPs, grouped by op
name / HLO category / source tf_op.

Usage::

    rows = profile_fn(step_fn, args)        # trace + parse in one call
    print(format_table(rows))

or through the ``mx.profiler`` facade: ``start()``/``stop()`` around any
device work, then ``device_dumps()`` renders this table.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
import tempfile
from collections import defaultdict

__all__ = ["parse_trace", "aggregate", "format_table", "profile_fn",
           "latest_session", "count_hlo_ops", "hlo_op_count"]


def latest_session(trace_dir):
    """Return the newest profile-session directory under *trace_dir*."""
    sessions = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    if not sessions:
        raise FileNotFoundError(f"no profile sessions under {trace_dir}")
    return sessions[-1]


def parse_trace(trace_dir):
    """Parse a ``jax.profiler`` trace directory into device-op records.

    Returns a list of dicts with keys: ``name``, ``category``, ``tf_op``,
    ``dur_us`` (device duration), ``flops``, ``bytes``, ``occurrences`` =1.
    Only events on the device "XLA Ops" lanes are returned (host python /
    runtime events are skipped) — these are the per-HLO-op executions.
    """
    session = latest_session(trace_dir)
    records = []
    for tj in sorted(glob.glob(os.path.join(session, "*.trace.json.gz"))):
        with gzip.open(tj, "rt") as f:
            trace = json.load(f)
        events = trace.get("traceEvents", [])
        # identify device pids and their "XLA Ops" / "Async XLA Ops" lanes
        device_pids = set()
        op_lanes = set()
        for e in events:
            if e.get("ph") != "M":
                continue
            if e.get("name") == "process_name" and \
                    "/device:" in e["args"].get("name", ""):
                device_pids.add(e["pid"])
            if e.get("name") == "thread_name" and \
                    "XLA Ops" in e["args"].get("name", ""):
                op_lanes.add((e["pid"], e["tid"]))
        for e in events:
            if e.get("ph") != "X" or e.get("pid") not in device_pids:
                continue
            if (e["pid"], e.get("tid")) not in op_lanes:
                continue
            args = e.get("args", {})
            dur_us = float(args.get("device_duration_ps", 0)) / 1e6 \
                or float(e.get("dur", 0.0))
            records.append({
                "name": e.get("name", "?"),
                "category": args.get("hlo_category", "?"),
                "tf_op": args.get("tf_op", ""),
                "source": args.get("source", ""),
                "long_name": args.get("long_name", ""),
                "dur_us": dur_us,
                "flops": int(args.get("model_flops", 0)),
                "bytes": int(args.get("raw_bytes_accessed",
                                      args.get("bytes_accessed", 0))),
            })
    return records


def aggregate(records, by="category"):
    """Group records by ``category`` | ``name`` | ``tf_op`` | ``source``.

    Returns rows sorted by total time desc: dicts with ``key``, ``calls``,
    ``dur_us``, ``flops``, ``bytes``, ``tflops`` (achieved), ``gbps``
    (achieved HBM bandwidth), ``pct`` of total device time.
    """
    groups = defaultdict(lambda: [0, 0.0, 0, 0])
    for r in records:
        k = r[by] or "<none>"
        g = groups[k]
        g[0] += 1
        g[1] += r["dur_us"]
        g[2] += r["flops"]
        g[3] += r["bytes"]
    total = sum(g[1] for g in groups.values()) or 1.0
    rows = []
    for k, (n, dur, fl, by_) in groups.items():
        rows.append({
            "key": k, "calls": n, "dur_us": dur, "flops": fl, "bytes": by_,
            "tflops": fl / dur / 1e6 if dur else 0.0,
            "gbps": by_ / dur / 1e3 if dur else 0.0,
            "pct": 100.0 * dur / total,
        })
    rows.sort(key=lambda r: -r["dur_us"])
    return rows


def format_table(rows, peak_tflops=None, limit=30):
    """Render aggregate rows as the reference-style per-op stats table."""
    lines = [f"{'Op':<44}{'Calls':>6}{'Time(us)':>11}{'%':>6}"
             f"{'TFLOP/s':>9}{'GB/s':>8}" +
             ("{:>6}".format("MFU%") if peak_tflops else ""),
             "-" * (84 + (6 if peak_tflops else 0))]
    for r in rows[:limit]:
        line = (f"{r['key'][:43]:<44}{r['calls']:>6}{r['dur_us']:>11.1f}"
                f"{r['pct']:>6.1f}{r['tflops']:>9.1f}{r['gbps']:>8.0f}")
        if peak_tflops:
            line += f"{100 * r['tflops'] / peak_tflops:>6.1f}"
        lines.append(line)
    tot = sum(r["dur_us"] for r in rows)
    lines.append(f"{'TOTAL':<44}{sum(r['calls'] for r in rows):>6}"
                 f"{tot:>11.1f}{100.0:>6.1f}")
    return "\n".join(lines)


def profile_fn(fn, *args, trace_dir=None, iters=2, warmup=True):
    """Trace ``fn(*args)`` on device and return per-op records.

    ``fn`` should be jit-compiled; it is run once for warmup (compile),
    then ``iters`` times inside the trace window with a device->host
    readback as the sync point (tunnel-safe, memory/TPU-tunnel-benchmarking).
    Durations are divided by ``iters`` so rows read as per-invocation.
    """
    import numpy as onp

    import jax

    if trace_dir is None:
        trace_dir = tempfile.mkdtemp(prefix="mxtpu_prof_")
    if warmup:
        jax.block_until_ready(fn(*args))
    jax.profiler.start_trace(trace_dir)
    try:
        out = None
        for _ in range(iters):
            out = fn(*args)
        leaves = [x for x in jax.tree_util.tree_leaves(out)
                  if hasattr(x, "dtype")]
        if leaves:
            onp.asarray(jax.device_get(leaves[0]))  # readback sync
    finally:
        jax.profiler.stop_trace()
    records = parse_trace(trace_dir)
    for r in records:
        r["dur_us"] /= iters
    return records


# ----------------------------------------------------------------------- #
# static HLO op counting — the sequencer-overhead metric
# ----------------------------------------------------------------------- #
# BASELINE.md r4 decode profile: the per-token cost floor is ~230 device
# ops x ~2.5 us of fixed sequencer cost each, and the BERT train step
# carries the same ~5,300-op gap.  The trace profiler above measures the
# overhead after the fact; these helpers measure the CAUSE — how many
# instructions the compiled program issues per invocation — so a fix
# (e.g. the stacked-layer scan decode) is assertable in CI on any
# backend, CPU included.

# instructions that exist in the HLO text but are not dispatched ops:
# parameters/constants are materialized buffers, tuple plumbing is free,
# bitcast is a layout annotation
_NON_EXEC_OPS = frozenset(
    ("parameter", "constant", "tuple", "get-tuple-element", "bitcast"))
# computation params and instruction result types may be tuples with
# internal spaces/parens — "(s32[], f32[2,4]{1,0})" — hence the loose
# ".*) ->" header match and the explicit tuple-type alternative
_COMP_HEADER = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)\s*->")
_CALLED_COMP = re.compile(r"(?:calls=|to_apply=)%?([\w.\-]+)")
_INSTR = re.compile(
    r"^\s+(?:ROOT\s+)?%?[\w.\-]+\s+=\s+(?:\([^)]*\)|\S+)\s+([\w\-]+)\(")


def count_hlo_ops(hlo_text):
    """Count the sequencer-visible instructions in optimized HLO text.

    Convention (matches how the device trace counts executed ops):

    - fusion bodies (``calls=``) and reduce/scatter/sort combinators
      (``to_apply=``) execute as part of ONE instruction in their caller
      — their inner instructions are not counted;
    - ``while`` bodies/conditions ARE counted, ONCE — a body that runs NL
      times still costs one body's worth of *distinct* program ops, which
      is exactly the collapse a stacked-layer ``lax.scan`` buys over an
      unrolled layer stack;
    - parameters, constants, and tuple/get-tuple-element/bitcast plumbing
      are free (no dispatched kernel).
    """
    excluded = set(_CALLED_COMP.findall(hlo_text))
    n = 0
    current = None
    for line in hlo_text.splitlines():
        m = _COMP_HEADER.match(line)
        if m:
            current = m.group(2)
            continue
        if line.startswith("}"):
            current = None
            continue
        if current is None or current in excluded:
            continue
        m = _INSTR.match(line)
        if m and m.group(1) not in _NON_EXEC_OPS:
            n += 1
    return n


def hlo_op_count(fn, *args, **kwargs):
    """Compile ``fn(*args, **kwargs)`` and return its optimized-HLO
    instruction count (see ``count_hlo_ops`` for the convention).

    ``fn`` may be a ``jax.jit`` object or a plain python callable (jitted
    here); args may be concrete arrays or ``jax.ShapeDtypeStruct``s — only
    shapes/dtypes matter, nothing is executed."""
    import jax

    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    compiled = fn.lower(*args, **kwargs).compile()
    return count_hlo_ops(compiled.as_text())
