"""Testing utilities.

Reference surface: ``python/mxnet/test_utils.py`` (SURVEY.md §3.2
"test_utils": ``assert_almost_equal`` with per-dtype tolerance,
``check_numeric_gradient`` finite differences vs autograd,
``check_consistency`` across contexts/dtypes, ``rand_ndarray``,
``with_seed``)."""
from __future__ import annotations

import functools
import random as pyrandom

import numpy as onp

from .base import MXNetError
from .context import cpu, current_context
from .ndarray.ndarray import NDArray, array

_DTYPE_TOL = {
    onp.dtype(onp.float16): (1e-2, 1e-2),
    onp.dtype(onp.float32): (1e-4, 1e-5),
    onp.dtype(onp.float64): (1e-6, 1e-8),
}
try:  # bfloat16 comes from ml_dtypes (registered by jax)
    import ml_dtypes as _mld
    _DTYPE_TOL[onp.dtype(_mld.bfloat16)] = (4e-2, 4e-2)
except ImportError:  # pragma: no cover
    pass


def default_rtol_atol(*arrays):
    rtol, atol = 1e-5, 1e-8
    for a in arrays:
        d = onp.dtype(getattr(a, "dtype", onp.float32))
        if d in _DTYPE_TOL:
            r, t = _DTYPE_TOL[d]
            rtol, atol = max(rtol, r), max(atol, t)
    return rtol, atol


def _np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_, b_ = _np(a), _np(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(a_, b_)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    onp.testing.assert_allclose(a_, b_, rtol=rtol, atol=atol,
                                err_msg=f"{names[0]} vs {names[1]}")


def same(a, b):
    return onp.array_equal(_np(a), _np(b))


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None):
    data = onp.random.uniform(-1, 1, size=shape).astype(dtype)
    nd = array(data, ctx=ctx)
    if stype != "default":
        return nd.tostype(stype)
    return nd


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite differences vs the autograd tape (reference anchor
    ``check_numeric_gradient``).  ``fn`` maps NDArrays -> scalar NDArray."""
    from . import autograd

    nds = [array(onp.asarray(x, onp.float32)) if not isinstance(x, NDArray)
           else x for x in inputs]
    for x in nds:
        x.attach_grad()
    with autograd.record():
        out = fn(*nds)
    out.backward()

    for i, x in enumerate(nds):
        base = onp.ascontiguousarray(x.asnumpy().astype(onp.float64))
        num = onp.zeros(base.shape, onp.float64)
        flat = base.reshape(-1)
        gflat = num.reshape(-1)
        for j in range(flat.size):
            pp, pm = flat.copy(), flat.copy()
            pp[j] += eps
            pm[j] -= eps
            def val(v):
                args = []
                for k, y in enumerate(nds):
                    if k == i:
                        args.append(array(v.reshape(x.shape).astype(onp.float32)))
                    else:
                        args.append(y.detach())
                with autograd.pause():
                    return float(fn(*args).asnumpy())
            gflat[j] = (val(pp) - val(pm)) / (2 * eps)
        assert_almost_equal(num, x.grad.asnumpy(), rtol=rtol, atol=atol,
                            names=(f"numeric_grad[{i}]", f"autograd[{i}]"))


def check_consistency(fn, inputs, dtypes=("float32",), rtol=None, atol=None):
    """Run ``fn`` under each dtype and compare results against the first
    (reference anchor ``check_consistency`` across ctx/dtype)."""
    ref = None
    for dt in dtypes:
        args = [array(_np(x).astype(dt)) for x in inputs]
        out = _np(fn(*args)).astype(onp.float64)
        if ref is None:
            ref = out
        else:
            r, t = default_rtol_atol(onp.zeros(1, dt))
            assert_almost_equal(out, ref, rtol=rtol or r, atol=atol or t)


def with_seed(seed=None):
    """Decorator: seed numpy/python/mx per test, report on failure
    (reference anchor ``with_seed``)."""

    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            from . import random as mxrandom
            s = seed if seed is not None else onp.random.randint(0, 2**31)
            onp.random.seed(s)
            pyrandom.seed(s)
            mxrandom.seed(s)
            try:
                return f(*args, **kwargs)
            except Exception:
                print(f"Test failed with seed {s} (set with_seed({s}) to "
                      f"reproduce)")
                raise
        return wrapper

    return deco


class environment:
    """Temporarily set environment variables (reference
    ``mx.util.environment`` test helper)."""

    def __init__(self, *args):
        import os
        self._os = os
        if len(args) == 2:
            self._vals = {args[0]: args[1]}
        else:
            self._vals = dict(args[0])

    def __enter__(self):
        self._old = {k: self._os.environ.get(k) for k in self._vals}
        for k, v in self._vals.items():
            if v is None:
                self._os.environ.pop(k, None)
            else:
                self._os.environ[k] = str(v)
        return self

    def __exit__(self, *a):
        for k, v in self._old.items():
            if v is None:
                self._os.environ.pop(k, None)
            else:
                self._os.environ[k] = v


def check_symbolic_forward(sym, args, expected, rtol=None, atol=None,
                           ctx=None):
    """Bind a symbol with ``args`` (list in list_arguments order or dict)
    and compare outputs against ``expected`` (reference
    ``check_symbolic_forward``)."""
    from . import ndarray as nd
    arg_names = sym.list_arguments()
    if isinstance(args, (list, tuple)):
        args = dict(zip(arg_names, args))
    args = {k: v if hasattr(v, "_data") else nd.array(v)
            for k, v in args.items()}
    ex = sym.bind(ctx=ctx, args=args, grad_req="null")
    outputs = ex.forward(is_train=False)
    assert len(outputs) == len(expected), \
        f"{len(outputs)} outputs != {len(expected)} expected"
    for o, e in zip(outputs, expected):
        assert_almost_equal(o, e, rtol, atol)
    return outputs


def check_symbolic_backward(sym, args, out_grads, expected_grads, rtol=None,
                            atol=None, grad_req="write", ctx=None):
    """Bind, forward, backward with ``out_grads``, compare argument
    gradients (reference ``check_symbolic_backward``)."""
    from . import ndarray as nd
    arg_names = sym.list_arguments()
    if isinstance(args, (list, tuple)):
        args = dict(zip(arg_names, args))
    args = {k: v if hasattr(v, "_data") else nd.array(v)
            for k, v in args.items()}
    if isinstance(expected_grads, (list, tuple)):
        expected_grads = dict(zip(arg_names, expected_grads))
    ex = sym.bind(ctx=ctx, args=args, grad_req=grad_req)
    ex.forward(is_train=True)
    ex.backward([g if hasattr(g, "_data") else nd.array(g)
                 for g in (out_grads if isinstance(out_grads, (list, tuple))
                           else [out_grads])])
    grads = ex.grad_dict
    for name, e in expected_grads.items():
        if e is None:
            continue
        assert name in grads, f"no gradient computed for {name}"
        assert_almost_equal(grads[name], e, rtol, atol,
                            names=(f"grad({name})", "expected"))
    return grads
