"""Device contexts.

Reference surface: ``python/mxnet/context.py`` — ``Context``, ``cpu()``,
``gpu(i)``, ``cpu_pinned()``, ``num_gpus``, default-context stack (SURVEY.md
§3.2 "context").  TPU-native mapping: a ``Context`` names a ``jax.Device``;
``mx.tpu(i)`` is first-class and ``mx.gpu(i)`` aliases the i-th accelerator so
reference scripts run unchanged.  Pinned/shared CPU variants map to plain host
memory (XLA manages transfers; there is no user-visible pinned pool on TPU).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError

__all__ = [
    "Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
    "num_gpus", "num_tpus", "current_context", "gpu_memory_info",
]


class Context:
    """A device context. ``devtype`` in {'cpu','tpu','gpu','cpu_pinned',
    'cpu_shared'}; 'gpu' is an alias for the local accelerator (TPU here)."""

    _default_ctx = threading.local()

    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {v: k for k, v in devtype2id.items()}

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devtype2id:
            raise MXNetError(f"unknown device type {device_type}")
        self.device_type = device_type
        self.device_id = device_id

    # -- identity ----------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devtype2id[self.device_type]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- jax mapping -------------------------------------------------------
    def jax_device(self) -> jax.Device:
        """Resolve to the concrete jax.Device backing this context.

        Always resolves within THIS process's addressable devices
        (``jax.local_devices``) — under multi-process SPMD the global
        device list leads with other hosts' devices, which cannot be
        device_put targets (SURVEY.md §4.4 process boundaries)."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _local_devices("cpu") if _has_platform("cpu") \
                else _local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        accel = _accel_devices()
        if not accel:
            # graceful degrade: no accelerator present, run on host
            devs = _local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        if self.device_id >= len(accel):
            raise MXNetError(
                f"context {self} out of range: {len(accel)} device(s) visible")
        return accel[self.device_id]

    # -- default-context stack --------------------------------------------
    @classmethod
    def default_ctx(cls) -> "Context":
        return getattr(cls._default_ctx, "value", None) or _default_context()

    def __enter__(self):
        self._old = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, *args):
        Context._default_ctx.value = self._old

    def empty_cache(self):
        """Reference: ``Context.empty_cache`` frees the GPU pool; XLA owns
        HBM on TPU so this is a no-op."""


def _has_platform(name: str) -> bool:
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


_ACCEL_CACHE = None


def _local_devices(platform: str = None):
    """This process's addressable devices, optionally of one backend.
    Falls back to filtering the global list by process_index on backends
    without the local/global distinction."""
    try:
        return jax.local_devices(backend=platform) if platform \
            else jax.local_devices()
    except Exception:
        devs = jax.devices(platform) if platform else jax.devices()
        try:
            me = jax.process_index()
        except Exception:
            me = 0
        local = [d for d in devs if getattr(d, "process_index", me) == me]
        return local or devs


def _accel_devices():
    """Non-CPU jax devices addressable by this process, else empty."""
    global _ACCEL_CACHE
    if _ACCEL_CACHE is None:
        devs = [d for d in _local_devices() if d.platform != "cpu"]
        _ACCEL_CACHE = devs
    return _ACCEL_CACHE


def _default_context() -> Context:
    return Context("tpu", 0) if _accel_devices() else Context("cpu", 0)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for the local accelerator so reference scripts using
    ``mx.gpu(i)`` target TPU chip *i* here."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_accel_devices())


def num_tpus() -> int:
    return len(_accel_devices())


def current_context() -> Context:
    return Context.default_ctx()


# HBM per chip by device-kind substring — the fallback gauge total when
# the backend exposes no allocator stats (e.g. tunneled devices)
_HBM_BYTES = (("v5 lite", 16 << 30), ("v5e", 16 << 30),
              ("v5p", 95 << 30), ("v4", 32 << 30), ("v6", 32 << 30),
              ("v3", 16 << 30), ("v2", 8 << 30))


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes of device HBM (reference:
    ``mx.context.gpu_memory_info``).

    Primary source: the backend allocator (``device.memory_stats``).
    Fallback (backends that return no stats, e.g. tunneled devices):
    live-buffer accounting over ``jax.live_arrays`` against the known
    per-chip HBM size — an upper bound on free memory, still a real
    gauge instead of the old silent ``(0, 0)``."""
    dev = Context("tpu", device_id).jax_device()
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        pass
    if stats and stats.get("bytes_limit"):
        total = stats["bytes_limit"]
        used = stats.get("bytes_in_use", 0)
        return (max(total - used, 0), total)
    # per-device shard bytes over jax.live_arrays() — the same walk the
    # telemetry memory accountant reconciles against (charging full
    # global nbytes would overcount sharded arrays mesh-wide)
    from .telemetry.memory import _devstr, live_device_bytes

    used = live_device_bytes().get(_devstr(dev), 0)
    kind = getattr(dev, "device_kind", "").lower()
    total = next((b for k, b in _HBM_BYTES if k in kind), 0)
    return (max(total - used, 0), total)
