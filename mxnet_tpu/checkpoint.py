"""``mx.checkpoint`` — orbax-backed sharded/async checkpointing.

Reference context (SURVEY.md §5.3/§5.4): the reference's fault-tolerance
story is "checkpoint every epoch and restart the launcher"; its formats are
the ``.params`` binary (kept, ndarray/serialization.py) + optimizer-state
pickles.  The TPU-native upgrade specified in SURVEY.md is "orbax
checkpoints (sharded, async) + auto-resume" — this module is that:

- :class:`CheckpointManager` — step-indexed directory of checkpoints with
  retention, async save (training continues while the previous step
  serializes), and sharding-aware restore (multi-host: each host writes its
  own shards).
- :func:`save` / :func:`restore` / :func:`latest_step` — functional API
  over a Gluon block (+ optional Trainer state).
- auto-resume: ``restore(...)`` with ``step=None`` loads the newest
  complete checkpoint, the launcher-restart recovery loop in one call.
"""
from __future__ import annotations

import os

import jax
import numpy as onp

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["CheckpointManager", "save", "restore", "latest_step"]


def _block_tree(block):
    """Block params as a flat name->jax.Array dict (structured names)."""
    params = block._collect_params_with_prefix()
    out = {}
    for name, p in params.items():
        if p._data is None:
            raise MXNetError(f"checkpoint: parameter {name} uninitialized")
        out[name] = p.data()._data
    return out


def _trainer_tree(trainer):
    if trainer is None:
        return None
    states = [s for s, made in zip(trainer._states, trainer._states_created)]
    return {
        "states": states,
        "created": list(trainer._states_created),
        "num_update": trainer._optimizer.num_update,
        "index_update_count": dict(trainer._optimizer._index_update_count),
    }


class CheckpointManager:
    """Step-indexed async checkpoints (orbax CheckpointManager facade)."""

    def __init__(self, directory, max_to_keep=5, async_save=True):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        opts = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                            enable_async_checkpointing=
                                            async_save)
        self._mgr = ocp.CheckpointManager(self._dir, options=opts)

    def save(self, step, block, trainer=None, extra=None):
        """Async-save params (+ trainer optimizer state, + extra numpy
        pytree) at ``step``."""
        import orbax.checkpoint as ocp
        tree = {"params": _block_tree(block)}
        t = _trainer_tree(trainer)
        if t is not None:
            tree["trainer"] = t
        if extra is not None:
            tree["extra"] = extra
        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        return step

    def restore(self, block, trainer=None, step=None):
        """Restore into ``block`` (and ``trainer``); ``step=None`` resumes
        from the newest complete checkpoint.  Returns the step restored, or
        None if the directory has no checkpoints (fresh start)."""
        import orbax.checkpoint as ocp
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                return None
        restored = self._mgr.restore(step)
        params = block._collect_params_with_prefix()
        loaded = restored["params"]
        for name, p in params.items():
            if name not in loaded:
                raise MXNetError(f"checkpoint missing parameter {name}")
            p._load_init(NDArray(jax.numpy.asarray(loaded[name])))
        if trainer is not None and "trainer" in restored:
            t = restored["trainer"]
            trainer._states = list(t["states"])
            trainer._states_created = [bool(x) for x in t["created"]]
            trainer._optimizer.num_update = int(t["num_update"])
            trainer._optimizer._index_update_count = {
                int(k) if str(k).isdigit() else k: int(v)
                for k, v in t["index_update_count"].items()}
        return step

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def wait_until_finished(self):
        """Block until pending async saves are durably written."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def save(directory, step, block, trainer=None):
    """One-shot save (sync): ``mx.checkpoint.save(dir, step, net, trainer)``."""
    mgr = CheckpointManager(directory, async_save=False)
    try:
        mgr.save(step, block, trainer)
        mgr.wait_until_finished()
    finally:
        mgr.close()
    return step


def restore(directory, block, trainer=None, step=None):
    """One-shot restore; ``step=None`` = auto-resume from newest."""
    mgr = CheckpointManager(directory, async_save=False)
    try:
        return mgr.restore(block, trainer, step)
    finally:
        mgr.close()


def latest_step(directory):
    mgr = CheckpointManager(directory, async_save=False)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()
