"""``mx.checkpoint`` — atomic, integrity-checked, async checkpoints
with bit-exact resume (ISSUE 15).

Reference context (SURVEY.md §5.3/§5.4): the reference's fault-tolerance
story is "checkpoint every epoch and restart the launcher"; its formats
are the ``.params`` binary + optimizer-state pickles, written in place —
a preempted host mid-write leaves a half-file the next run loads or
crashes on.  The TPU-native upgrade specified there (orbax-style
sharded/async checkpoints + auto-resume) is implemented natively here so
every property the recovery loop stands on is explicit and testable:

- **Commit-or-invisible saves.**  Every step is written to a hidden
  temp directory (``.tmp-step_XXXXXXXX-<pid>-<nonce>``), each array
  file and the manifest are fsynced, the directory itself is fsynced,
  and only then is it renamed to ``step_XXXXXXXX`` (one atomic rename
  on POSIX).  A rank SIGKILLed mid-save leaves a temp directory that
  restore reports (``checkpoint_corrupt`` event) and cleans up — never
  a half-checkpoint that parses.
- **Integrity-checked restore.**  ``MANIFEST.json`` records every
  array's file, shape, dtype, byte size, and CRC32.  ``restore(step=
  None)`` walks steps newest-first, verifies each candidate, emits a
  loud ``checkpoint_corrupt`` event for any damaged/incomplete one and
  falls back to the newest verifiable step — corruption is an event,
  never a crash.  An explicitly requested ``step=`` that fails
  verification raises a clean :class:`MXNetError` instead.
- **Async save without donation hazards.**  ``async_save=True``
  snapshots device→host *synchronously inside* ``save()`` (the only
  part the training loop waits for — measured as ``snapshot_s`` and in
  ``benchmark/step_profile.py``); the atomic write happens on a
  background writer thread.  The fused train step donates weight /
  optimizer-state / accumulator buffers into the next executable, so
  the snapshot MUST complete before the next step dispatches — which
  it does, because ``save()`` doesn't return until the host copy is
  done.  A failed background write surfaces on the next
  ``save()``/``wait_until_finished()``.
- **Bit-exact resume.**  A checkpoint captures everything the step
  function consumes: params, optimizer states / ``num_update`` /
  per-index update counts, the fused-step accumulation-window position
  plus the device accumulator ring(s) for a mid-window save (a
  mid-window save on the non-fused path refuses loudly instead of
  silently dropping the partial window), ``amp`` loss-scaler state,
  the ``mx.random`` root key, and — via ``extra=`` — the data-pipeline
  cursor (epoch + batch index; restore fast-forwards the sampler with
  ``DataLoader.iter_from``, never replays batches).  Kill-and-resume
  equals uninterrupted, pinned by the chaos parity tests.
- **Resharding restore.**  Arrays are stored as full logical host
  values; restore places each one with the *target* parameter's
  current sharding (``Parameter._load_init``), so a checkpoint saved
  on the 8-device dryrun mesh restores onto a 1-device mesh and vice
  versa.  A shape mismatch raises an :class:`MXNetError` naming both
  the saved and the current mesh — no silent replication.

Known limits (documented in docs/CHECKPOINT.md): one writer per
directory (multi-host pods give each process its own directory, e.g.
``$MXNET_CHECKPOINT_DIR/rank<r>``); the RNG capture covers the calling
thread's root key (traced draws ride the trace-key operand and need no
capture); array payloads are buffered in host memory during write.

Chaos sites: ``checkpoint.save`` fires after the temp files are
durable but *before* the commit rename (a ``kill`` there is the
preempted-mid-save scenario), ``checkpoint.restore`` fires at restore
entry.  See ``MXNET_FAULT_INJECT`` in docs/ENV_VARS.md.
"""
from __future__ import annotations

import io
import json
import os
import queue as _queue
import re
import shutil
import threading
import time
import uuid
import zlib

import jax
import jax.numpy as jnp
import numpy as onp

from . import telemetry
from .base import MXNetError
from .ndarray.ndarray import NDArray
from .telemetry.faults import fault_point

__all__ = ["CheckpointManager", "save", "restore", "latest_step",
           "verify_step", "restart_count"]

FORMAT_VERSION = 1
_MANIFEST = "MANIFEST.json"
_STEP_RE = re.compile(r"^step_(\d{8,})$")
_TMP_PREFIX = ".tmp-"


def _step_dirname(step):
    return f"step_{int(step):08d}"


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def restart_count():
    """This process's pod-restart generation: 0 on the first launch,
    incremented by the ``tools/launch.py --restarts`` supervisor on
    every respawn (``MXNET_RESTART_COUNT``).  Rank code uses it to
    behave differently across attempts — e.g. a chaos script arms its
    ``MXNET_FAULT_INJECT`` rule only when ``restart_count() == 0`` so
    an injected kill doesn't recur forever."""
    try:
        return int(os.environ.get("MXNET_RESTART_COUNT", "0"))
    except ValueError:
        return 0


# --------------------------------------------------------------------- #
# pytree <-> (json structure, host array leaves)
# --------------------------------------------------------------------- #

def _is_array(x):
    return isinstance(x, (jax.Array, onp.ndarray, onp.generic, NDArray))


def _enc(x, leaves):
    """Encode a checkpoint tree into a JSON-able structure + a flat
    list of HOST numpy leaves.  ``jax.Array`` leaves are device_get
    here — this is the synchronous device→host snapshot, and the only
    part of an async save the training loop waits for."""
    if isinstance(x, NDArray):
        x = x._data
    if isinstance(x, jax.Array):
        leaves.append(onp.asarray(jax.device_get(x)))
        return {"@arr": len(leaves) - 1}
    if isinstance(x, (onp.ndarray, onp.generic)):
        leaves.append(onp.asarray(x))
        return {"@arr": len(leaves) - 1}
    if x is None or isinstance(x, (bool, int, float, str)):
        return {"@val": x}
    if isinstance(x, (list, tuple)):
        return {"@seq": [_enc(v, leaves) for v in x],
                "tuple": isinstance(x, tuple)}
    if isinstance(x, dict):
        items = []
        for k, v in x.items():
            if not isinstance(k, (str, int)):
                raise MXNetError(
                    f"checkpoint: unsupported dict key type "
                    f"{type(k).__name__} (str/int only)")
            items.append([["i" if isinstance(k, int) else "s", k],
                          _enc(v, leaves)])
        return {"@dict": items}
    raise MXNetError(
        f"checkpoint: unsupported leaf type {type(x).__name__}")


def _dec(node, leaves, leaf_fn=None):
    if "@arr" in node:
        a = leaves[node["@arr"]]
        return leaf_fn(a) if leaf_fn is not None else a
    if "@val" in node:
        return node["@val"]
    if "@seq" in node:
        seq = [_dec(v, leaves, leaf_fn) for v in node["@seq"]]
        return tuple(seq) if node.get("tuple") else seq
    if "@dict" in node:
        out = {}
        for (kt, k), v in node["@dict"]:
            out[int(k) if kt == "i" else k] = _dec(v, leaves, leaf_fn)
        return out
    raise MXNetError(f"checkpoint: malformed structure node {node!r}")


# --------------------------------------------------------------------- #
# training-state capture
# --------------------------------------------------------------------- #

def _block_tree(block):
    """Block params as a flat name->jax.Array dict (structured names)."""
    params = block._collect_params_with_prefix()
    out = {}
    for name, p in params.items():
        if p._data is None:
            raise MXNetError(f"checkpoint: parameter {name} uninitialized")
        out[name] = p.data()._data
    return out


def _trainer_tree(trainer):
    """Everything the step function consumes beyond the params: the
    optimizer (states, schedule counters), the gradient-accumulation
    window (position + the device accumulator ring of every cached
    FusedStep — a mid-window save on the non-fused path has no ring to
    record and refuses loudly), and the amp loss-scaler state."""
    if trainer is None:
        return None
    rings = [list(fs._accum) for fs in trainer._fused_steps.values()
             if getattr(fs, "_accum", None)]
    if trainer._window_pos != 0 and not rings:
        raise MXNetError(
            f"checkpoint: mid-accumulation-window save (micro-batch "
            f"{trainer._window_pos}/{trainer._update_interval}) without "
            "a fused-step accumulator ring: the partial window lives in "
            "grad buffers this checkpoint does not capture, so resume "
            "could not be bit-exact. Save at the window boundary, or "
            "drive the window with fused_step() (its device ring is "
            "captured).")
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    return {
        "states": list(trainer._states),
        "created": list(trainer._states_created),
        "num_update": int(trainer._optimizer.num_update),
        "index_update_count": {
            int(k): int(v)
            for k, v in trainer._optimizer._index_update_count.items()},
        "window_pos": int(trainer._window_pos),
        "accum": rings,
        "loss_scaler": None if scaler is None else {
            "loss_scale": float(scaler.loss_scale),
            "unskipped": int(scaler._unskipped)},
    }


def _rng_tree():
    from . import random as mxrandom

    return mxrandom.get_state()


def _mesh_info():
    devs = jax.devices()
    return {"device_count": len(devs),
            "platform": devs[0].platform if devs else "unknown",
            "process_index": jax.process_index(),
            "process_count": jax.process_count()}


def _apply_params(block, loaded, saved_mesh):
    """Load ``name -> host array`` into the block, placing every value
    with the parameter's CURRENT sharding (``_load_init``), so a
    checkpoint reshards onto whatever mesh the params live on now.  A
    shape mismatch is a clean error naming both meshes — never a
    silent replication of wrong-shaped data."""
    params = block._collect_params_with_prefix()
    here = _mesh_info()
    for name, p in params.items():
        if name not in loaded:
            raise MXNetError(f"checkpoint missing parameter {name}")
        arr = loaded[name]
        if p.shape and None not in p.shape and \
                tuple(arr.shape) != tuple(p.shape):
            raise MXNetError(
                f"checkpoint: parameter {name} was saved with shape "
                f"{tuple(arr.shape)} on a {saved_mesh.get('device_count')}"
                f"-device {saved_mesh.get('platform')} mesh but the "
                f"current parameter has shape {tuple(p.shape)} on a "
                f"{here['device_count']}-device {here['platform']} mesh "
                "— the logical shapes must match for a reshard; "
                "rebuild the block to the saved geometry or pass the "
                "matching checkpoint")
        p._load_init(NDArray(jnp.asarray(arr)))


def _apply_trainer(trainer, t):
    trainer._states = [None if s is None else
                       jax.tree.map(jnp.asarray, s) for s in t["states"]]
    trainer._states_created = [bool(x) for x in t["created"]]
    trainer._optimizer.num_update = int(t["num_update"])
    trainer._optimizer._index_update_count = {
        int(k): int(v) for k, v in t["index_update_count"].items()}
    trainer._window_pos = int(t.get("window_pos", 0))
    # every cached FusedStep's ring is stale relative to the restored
    # window: drop them, and stage the saved ring(s) for adoption on
    # the next fused call (matched by shape — see FusedStep.__call__)
    for fs in trainer._fused_steps.values():
        fs._accum = None
    trainer._pending_accum = [
        [jnp.asarray(a) for a in ring] for ring in t.get("accum", [])]
    ls = t.get("loss_scaler")
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if ls is not None and scaler is not None:
        scaler.loss_scale = float(ls["loss_scale"])
        scaler._unskipped = int(ls["unskipped"])


# --------------------------------------------------------------------- #
# manager
# --------------------------------------------------------------------- #

class _Corrupt(Exception):
    """Internal: a step directory failed verification (why in args)."""


class CheckpointManager:
    """Step-indexed directory of atomic checkpoints with retention,
    async save, integrity-checked auto-resume, and bit-exact
    training-state capture.  ``directory=None`` uses
    ``MXNET_CHECKPOINT_DIR`` (exported per rank by
    ``tools/launch.py --checkpoint-dir``)."""

    def __init__(self, directory=None, max_to_keep=5, async_save=True):
        directory = directory or os.environ.get("MXNET_CHECKPOINT_DIR")
        if not directory:
            raise MXNetError(
                "CheckpointManager: no directory given and "
                "MXNET_CHECKPOINT_DIR is unset")
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        if max_to_keep is not None and int(max_to_keep) < 1:
            raise MXNetError("max_to_keep must be >= 1 (or None)")
        self._max_to_keep = None if max_to_keep is None else int(max_to_keep)
        self._async = bool(async_save)
        self._lock = threading.Lock()
        self._jobs = None
        self._writer = None
        self._error = None
        self._closed = False

    @property
    def directory(self):
        return self._dir

    # -- save ----------------------------------------------------------- #
    def save(self, step, block, trainer=None, extra=None):
        """Checkpoint ``step``: params (+ trainer training state, + an
        ``extra`` pytree such as the data cursor).  Synchronous part:
        the device→host snapshot (donation-safe — completes before the
        next fused step can donate the buffers).  With
        ``async_save=True`` the atomic write then happens on the
        background writer; a failed write raises here on the NEXT call
        (or on ``wait_until_finished``)."""
        self._raise_pending()
        if self._closed:
            raise MXNetError("CheckpointManager is closed")
        step = int(step)
        t0 = time.perf_counter()
        tree = {"params": _block_tree(block), "rng": _rng_tree()}
        t = _trainer_tree(trainer)
        if t is not None:
            tree["trainer"] = t
        if extra is not None:
            tree["extra"] = extra
        leaves = []
        struct = _enc(tree, leaves)
        snapshot_s = time.perf_counter() - t0
        telemetry.histogram("checkpoint_save_seconds",
                            phase="snapshot").observe(snapshot_s)
        if self._async:
            self._ensure_writer()
            self._jobs.put((step, struct, leaves, snapshot_s))
        else:
            self._write_step(step, struct, leaves, snapshot_s)
        return step

    def _ensure_writer(self):
        with self._lock:
            if self._writer is None or not self._writer.is_alive():
                self._jobs = _queue.Queue()
                self._writer = threading.Thread(
                    target=self._writer_loop, name="mxnet-ckpt-writer",
                    daemon=True)
                self._writer.start()

    def _writer_loop(self):
        while True:
            job = self._jobs.get()
            try:
                if job is None:
                    return
                try:
                    self._write_step(*job)
                except Exception as e:
                    with self._lock:
                        if self._error is None:
                            self._error = e
            finally:
                self._jobs.task_done()

    def _raise_pending(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise MXNetError(
                f"checkpoint: a background save failed: {err}") from err

    def _write_step(self, step, struct, leaves, snapshot_s):
        t0 = time.perf_counter()
        final = os.path.join(self._dir, _step_dirname(step))
        tmp = os.path.join(
            self._dir, f"{_TMP_PREFIX}{_step_dirname(step)}-"
                       f"{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            arrays = []
            total = 0
            for i, a in enumerate(leaves):
                buf = io.BytesIO()
                onp.save(buf, a, allow_pickle=False)
                data = buf.getvalue()
                fname = f"arr_{i:05d}.npy"
                with open(os.path.join(tmp, fname), "wb") as fh:
                    fh.write(data)
                    fh.flush()
                    os.fsync(fh.fileno())
                arrays.append({"file": fname, "shape": list(a.shape),
                               "dtype": str(a.dtype),
                               "bytes": len(data),
                               "crc32": zlib.crc32(data) & 0xFFFFFFFF})
                total += len(data)
            manifest = {"format": FORMAT_VERSION, "step": step,
                        "saved_unix": time.time(),
                        "library": "mxnet_tpu",
                        "mesh": _mesh_info(), "tree": struct,
                        "arrays": arrays}
            with open(os.path.join(tmp, _MANIFEST), "w",
                      encoding="utf-8") as fh:
                json.dump(manifest, fh)
                fh.flush()
                os.fsync(fh.fileno())
            _fsync_dir(tmp)
            # the injected-preemption point: everything is durably in
            # the temp dir, nothing is committed — a kill here leaves
            # a checkpoint that never becomes visible
            fault_point("checkpoint.save", step=step)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            # a failed (or fault-aborted) write cleans up its own temp
            # dir: same-pid temp dirs are deliberately exempt from the
            # restore-time sweep (they may be a LIVE writer's), so an
            # abandoned one would otherwise linger for this process's
            # whole life
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _fsync_dir(self._dir)
        write_s = time.perf_counter() - t0
        telemetry.histogram("checkpoint_save_seconds",
                            phase="write").observe(write_s)
        telemetry.counter("checkpoints_saved_total").inc()
        telemetry.emit("checkpoint_saved", step=step, dir=self._dir,
                       bytes=total, arrays=len(arrays),
                       snapshot_s=round(snapshot_s, 6),
                       write_s=round(write_s, 6),
                       async_save=self._async)
        self._retain()

    def _retain(self):
        if self._max_to_keep is None:
            return
        steps = self.all_steps()
        while len(steps) > self._max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(os.path.join(self._dir, _step_dirname(victim)),
                          ignore_errors=True)

    # -- discovery / verification --------------------------------------- #
    def all_steps(self):
        """Committed step numbers, ascending (no integrity check —
        see :meth:`verify` / :meth:`latest_step`)."""
        out = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return out
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self._dir, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _load_verified(self, step, keep_arrays=True):
        """Read + integrity-check one step: manifest parse, per-array
        byte size and CRC32.  Returns (manifest, leaves) or raises
        :class:`_Corrupt` naming what failed."""
        d = os.path.join(self._dir, _step_dirname(step))
        mpath = os.path.join(d, _MANIFEST)
        try:
            with open(mpath, "r", encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            raise _Corrupt(f"manifest unreadable ({e})")
        if manifest.get("format") != FORMAT_VERSION:
            raise _Corrupt(
                f"format {manifest.get('format')!r} != {FORMAT_VERSION}")
        leaves = []
        for meta in manifest.get("arrays", []):
            fpath = os.path.join(d, meta["file"])
            try:
                with open(fpath, "rb") as fh:
                    data = fh.read()
            except OSError as e:
                raise _Corrupt(f"array {meta['file']} unreadable ({e})")
            if len(data) != meta["bytes"]:
                raise _Corrupt(
                    f"array {meta['file']} truncated "
                    f"({len(data)} != {meta['bytes']} bytes)")
            if (zlib.crc32(data) & 0xFFFFFFFF) != meta["crc32"]:
                raise _Corrupt(f"array {meta['file']} checksum mismatch")
            if keep_arrays:
                try:
                    leaves.append(onp.load(io.BytesIO(data),
                                           allow_pickle=False))
                except ValueError as e:
                    raise _Corrupt(
                        f"array {meta['file']} undecodable ({e})")
        return manifest, leaves

    def verify(self, step):
        """(ok, why) for one committed step — why is None when the
        checkpoint is complete and every checksum matches."""
        try:
            self._load_verified(step, keep_arrays=False)
            return True, None
        except _Corrupt as e:
            return False, str(e)

    def latest_step(self):
        """The newest step that passes verification (corrupt newer
        steps are skipped with a ``checkpoint_corrupt`` event, exactly
        like ``restore(step=None)``)."""
        for step in reversed(self.all_steps()):
            ok, why = self.verify(step)
            if ok:
                return step
            self._report_corrupt(step, why)
        return None

    def _report_corrupt(self, step, why):
        telemetry.counter("checkpoints_corrupt_total").inc()
        telemetry.emit("checkpoint_corrupt", dir=self._dir, step=step,
                       why=why)

    def _sweep_tmp(self):
        """Leftover ``.tmp-*`` directories are saves a dead process
        never committed (the kill-mid-save scenario): report each one
        loudly and remove it.  Temp dirs carrying THIS process's pid
        are skipped — they may be a live async writer's in-flight
        save (restore-during-save must not destroy it); a dead
        process's leftovers always carry a different pid."""
        try:
            names = os.listdir(self._dir)
        except OSError:
            return
        own = f"-{os.getpid()}-"
        for name in names:
            if not name.startswith(_TMP_PREFIX) or own in name:
                continue
            self._report_corrupt(
                None, f"interrupted save (uncommitted {name})")
            shutil.rmtree(os.path.join(self._dir, name),
                          ignore_errors=True)

    # -- restore -------------------------------------------------------- #
    def restore(self, block, trainer=None, step=None, return_extra=False):
        """Restore into ``block`` (and ``trainer``); ``step=None``
        auto-resumes from the newest VERIFIABLE checkpoint, skipping
        incomplete/corrupt ones with a ``checkpoint_corrupt`` event
        per skip.  Returns the restored step (or ``(step, extra)``
        with ``return_extra=True``), or None when nothing restorable
        exists.  An explicit ``step=`` that is missing or fails
        verification raises :class:`MXNetError`."""
        fault_point("checkpoint.restore", step=step)
        self._sweep_tmp()
        if step is not None:
            step = int(step)
            if step not in self.all_steps():
                raise MXNetError(
                    f"checkpoint: no step {step} in {self._dir}")
            try:
                manifest, leaves = self._load_verified(step)
            except _Corrupt as e:
                self._report_corrupt(step, str(e))
                raise MXNetError(
                    f"checkpoint: step {step} in {self._dir} failed "
                    f"verification: {e}") from e
            return self._apply(manifest, leaves, block, trainer,
                               return_extra)
        for s in reversed(self.all_steps()):
            try:
                manifest, leaves = self._load_verified(s)
            except _Corrupt as e:
                self._report_corrupt(s, str(e))
                continue
            return self._apply(manifest, leaves, block, trainer,
                               return_extra)
        return None

    def _apply(self, manifest, leaves, block, trainer, return_extra):
        tree = _dec(manifest["tree"], leaves)
        saved_mesh = manifest.get("mesh", {})
        _apply_params(block, tree["params"], saved_mesh)
        if trainer is not None and tree.get("trainer") is not None:
            _apply_trainer(trainer, tree["trainer"])
        if tree.get("rng") is not None:
            from . import random as mxrandom

            mxrandom.set_state(tree["rng"])
        step = int(manifest["step"])
        telemetry.emit("checkpoint_restored", dir=self._dir, step=step,
                       arrays=len(leaves))
        if return_extra:
            return step, tree.get("extra")
        return step

    # -- lifecycle ------------------------------------------------------ #
    def wait_until_finished(self):
        """Block until pending async saves are durably committed, and
        surface any background write error."""
        if self._jobs is not None:
            self._jobs.join()
        self._raise_pending()

    def close(self, timeout=60.0):
        """Flush pending saves and stop the writer.  A background
        write error still pending here raises (close is the last
        chance to hear about it), and so does a writer still mid-write
        after ``timeout`` seconds — a silently abandoned final
        checkpoint would be swept as corrupt by the next run."""
        with self._lock:
            if self._closed:
                writer = None
            else:
                self._closed = True
                writer = self._writer
        if writer is not None:
            self._jobs.put(None)   # poison pill: the writer loop exits
            writer.join(timeout=timeout)
            if writer.is_alive():
                raise MXNetError(
                    f"checkpoint: the background writer is still "
                    f"writing after {timeout}s — the pending save has "
                    "NOT committed; wait_until_finished() (or a larger "
                    "close timeout) before exiting, or the next run "
                    "will sweep it as an interrupted save")
        self._raise_pending()


# --------------------------------------------------------------------- #
# functional one-shots
# --------------------------------------------------------------------- #

def save(directory, step, block, trainer=None, extra=None):
    """One-shot atomic save (sync):
    ``mx.checkpoint.save(dir, step, net, trainer)``."""
    mgr = CheckpointManager(directory, max_to_keep=None, async_save=False)
    try:
        mgr.save(step, block, trainer, extra=extra)
    finally:
        mgr.close()
    return step


def restore(directory, block, trainer=None, step=None, return_extra=False):
    """One-shot restore; ``step=None`` = auto-resume from the newest
    verifiable checkpoint (corrupt ones skipped loudly)."""
    mgr = CheckpointManager(directory, max_to_keep=None, async_save=False)
    try:
        return mgr.restore(block, trainer, step, return_extra=return_extra)
    finally:
        mgr.close()


def latest_step(directory):
    mgr = CheckpointManager(directory, max_to_keep=None, async_save=False)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


def verify_step(directory, step):
    """(ok, why) integrity verdict for one step — the offline tool for
    'is this checkpoint loadable'."""
    mgr = CheckpointManager(directory, max_to_keep=None, async_save=False)
    try:
        return mgr.verify(int(step))
    finally:
        mgr.close()
