"""``mx.name`` — symbol naming scopes (reference ``python/mxnet/name.py``:
``NameManager``/``Prefix``) and ``mx.AttrScope`` (``python/mxnet/attribute.py``).

The symbol builders consult the active NameManager for auto-names and the
active AttrScope for extra node attrs (the reference's ``ctx_group`` /
``lr_mult`` attr plumbing).
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "AttrScope", "current_name_manager",
           "current_attrs"]


class NameManager:
    """Assigns names to unnamed symbols; ``with NameManager():`` scopes it."""

    _tls = threading.local()

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        self._old = getattr(NameManager._tls, "value", None)
        NameManager._tls.value = self
        return self

    def __exit__(self, *a):
        NameManager._tls.value = self._old


class Prefix(NameManager):
    """Prepends a fixed prefix to every auto-name (reference ``Prefix``)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return name if name else self._prefix + super().get(None, hint)


def current_name_manager():
    return getattr(NameManager._tls, "value", None)


class AttrScope:
    """``with mx.AttrScope(ctx_group='dev1'):`` — attrs attached to every
    symbol created in scope (reference ``AttrScope``).  ``ctx_group`` maps
    onto GSPMD sharding annotations rather than device placement (PARITY)."""

    _tls = threading.local()

    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}
        self._old = None

    def get(self, attrs=None):
        out = dict(self._attrs)
        if attrs:
            out.update(attrs)
        return out

    def __enter__(self):
        self._old = getattr(AttrScope._tls, "value", None)
        if self._old is not None:
            merged = dict(self._old._attrs)
            merged.update(self._attrs)
            self._attrs = merged
        AttrScope._tls.value = self
        return self

    def __exit__(self, *a):
        AttrScope._tls.value = self._old


def current_attrs():
    scope = getattr(AttrScope._tls, "value", None)
    return dict(scope._attrs) if scope is not None else {}
