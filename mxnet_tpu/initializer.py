"""Weight initializers.

Reference surface: ``python/mxnet/initializer.py`` (SURVEY.md §3.2
"initializer": Xavier/MSRAPrelu/Normal/Uniform/Orthogonal/Bilinear/LSTMBias/
Constant/Load/Mixed; string-serialized init in param files).

TPU-native: each initializer is a pure function of ``(key, shape, dtype)``
using ``jax.random`` so parameter init composes with jit/sharded init later;
the imperative surface (``init(name, arr)``) matches the reference.
"""
from __future__ import annotations

import json
import re

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError

__all__ = [
    "Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
    "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "Load",
    "Mixed", "register", "create",
]

_REGISTRY: dict = {}


def register(klass):
    """Register an initializer under its lowercase class name (reference
    anchor ``@mx.init.register``)."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs):
    if init is None:
        return None
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _REGISTRY:
            raise MXNetError(f"unknown initializer {init}")
        return _REGISTRY[name](**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class InitDesc(str):
    """Parameter name string carrying init attrs (reference ``InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base class.  Subclasses implement ``_init_weight(name, key, shape,
    dtype) -> jax array``; pattern-dispatch on the parameter name mirrors the
    reference (`_init_bias`, `_init_gamma`, ...)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        """Serialize as ``[name, kwargs]`` JSON (stored in .params files by
        the reference)."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    # -- keyed functional surface (TPU-native) ---------------------------- #
    def generate(self, name: str, key, shape, dtype=jnp.float32):
        """Pure: produce the initialized array for parameter ``name``."""
        name = name.lower()
        if name.endswith("gamma"):
            return self._init_one(key, shape, dtype)
        if name.endswith("beta") or name.endswith("bias"):
            return self._init_zero(key, shape, dtype)
        if "running_mean" in name or "moving_mean" in name:
            return self._init_zero(key, shape, dtype)
        if ("running_var" in name or "moving_var" in name
                or "moving_avg" in name):
            return self._init_one(key, shape, dtype)
        if name.endswith("min") or name.endswith("max"):
            return self._init_zero(key, shape, dtype)
        if name.endswith("weight") or True:
            return self._init_weight(name, key, shape, dtype)

    def __call__(self, desc, arr):
        """Imperative surface: initialize NDArray ``arr`` in place.

        An explicit initializer attached to the parameter
        (``desc.attrs['__init__']``, reference ``InitDesc`` protocol)
        BYPASSES the name-suffix dispatch — the reference calls
        ``create(init)._init_weight(desc, arr)`` directly, so e.g.
        ``bias_initializer='ones'`` must not be overridden to zeros."""
        from . import random as mxrandom

        name = str(desc)
        init_override = getattr(desc, "attrs", {}).get("__init__", "")
        if init_override:
            if isinstance(init_override, Initializer):
                ini = init_override
            elif init_override.startswith("["):
                spec = json.loads(init_override)
                ini = create(spec[0], **spec[1])
            else:
                ini = create(init_override)
            if type(ini).__call__ is not Initializer.__call__:
                # Load/Mixed style initializers define their own imperative
                # surface; hand them the array without the override attr.
                ini(InitDesc(name), arr)
                return
            val = ini._init_weight(name, mxrandom.next_key(), arr.shape,
                                   arr._data.dtype)
        else:
            val = self.generate(name, mxrandom.next_key(), arr.shape,
                                arr._data.dtype)
        arr._rebind(jnp.asarray(val, arr._data.dtype))

    init_weight = __call__

    # -- primitive fills -------------------------------------------------- #
    def _init_zero(self, key, shape, dtype):
        return jnp.zeros(shape, dtype)

    def _init_one(self, key, shape, dtype):
        return jnp.ones(shape, dtype)

    def _init_weight(self, name, key, shape, dtype):
        raise NotImplementedError

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, key, shape, dtype):
        return jnp.zeros(shape, dtype)


@register
class One(Initializer):
    def _init_weight(self, name, key, shape, dtype):
        return jnp.ones(shape, dtype)


_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, key, shape, dtype):
        v = self.value
        if hasattr(v, "asnumpy"):
            v = v.asnumpy()
        return jnp.broadcast_to(jnp.asarray(v, dtype), shape)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, key, shape, dtype):
        return jax.random.uniform(key, shape, jnp.float32, -self.scale,
                                  self.scale).astype(dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, key, shape, dtype):
        return (jax.random.normal(key, shape, jnp.float32)
                * self.sigma).astype(dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, key, shape, dtype):
        nout = shape[0]
        nin = int(onp.prod(shape[1:])) if len(shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), jnp.float32, -1.0, 1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin), jnp.float32)
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        return (self.scale * q.reshape(shape)).astype(dtype)


@register
class Xavier(Initializer):
    """Reference anchor ``Xavier``: factor from fan-in/out, uniform /
    gaussian variants."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, key, shape, dtype):
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier requires ndim>=2 param, got shape {shape} for {name}")
        hw_scale = float(onp.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type}")
        scale = float(onp.sqrt(self.magnitude / factor))
        if self.rnd_type == "uniform":
            return jax.random.uniform(key, shape, jnp.float32, -scale,
                                      scale).astype(dtype)
        if self.rnd_type == "gaussian":
            return (jax.random.normal(key, shape, jnp.float32)
                    * scale).astype(dtype)
        raise MXNetError(f"bad rnd_type {self.rnd_type}")


@register
class MSRAPrelu(Xavier):
    """He init (reference anchor ``MSRAPrelu``)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel for Deconvolution."""

    def _init_weight(self, name, key, shape, dtype):
        weight = onp.zeros(int(onp.prod(shape)), onp.float32)
        f = onp.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - _abs(x / f - c)) * (1 - _abs(y / f - c))
        return jnp.asarray(weight.reshape(shape), dtype)


def _abs(x):
    return x if x >= 0 else -x


@register
class LSTMBias(Initializer):
    """Forget-gate bias = ``forget_bias``, others 0 (reference anchor
    ``LSTMBias``); layout i,f,c,o in 4 equal chunks."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, key, shape, dtype):
        b = onp.zeros(shape, onp.float32)
        num_hidden = shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        return jnp.asarray(b, dtype)


@register
class Load(Initializer):
    """Init from a dict of arrays, falling back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, desc, arr):
        name = str(desc)
        if name in self.param:
            src = self.param[name]
            data = src.asnumpy() if hasattr(src, "asnumpy") else onp.asarray(src)
            if tuple(data.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Load: shape mismatch for {name}: {data.shape} vs "
                    f"{arr.shape}")
            arr._rebind(jnp.asarray(data, arr._data.dtype))
        elif self.default_init is not None:
            self.default_init(desc, arr)
        else:
            raise MXNetError(f"Load: no init for {name}")


@register
class Mixed(Initializer):
    """Pattern-dispatched initializer list (reference anchor ``Mixed``)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise MXNetError("patterns/initializers length mismatch")
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, desc, arr):
        for pat, ini in self.map:
            if pat.match(str(desc)):
                ini(desc, arr)
                return
        raise MXNetError(
            f"Mixed: no pattern matched {desc}; add a '.*' catch-all")
