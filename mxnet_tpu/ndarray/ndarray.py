"""NDArray — the imperative tensor handle.

Reference surface: ``python/mxnet/ndarray/ndarray.py`` + ``src/ndarray/``
(SURVEY.md §3.1 "NDArray": async tensor handle over an engine-scheduled
chunk; ``WaitToRead``, ``CopyFromTo``, autograd entry, in-place ops).

TPU-native redesign (SURVEY.md §7 "Arrays"): an ``NDArray`` is a thin handle
over a ``jax.Array`` — async *by construction* (JAX dispatch returns
futures), so the reference's dependency engine disappears:
``WaitToRead == block_until_ready``.  In-place operations rebind the handle
to a fresh functional value (``x += y`` => ``x._data = x._data + y``): user
code keeps MXNet's mutable-looking semantics while every underlying value
stays immutable for XLA (this is SURVEY.md §7 "hard part 1").  The autograd
entry (``_autograd_node/_autograd_idx``) points into the tape exactly like
the reference NDArray's ``autograd_entry_``.
"""
from __future__ import annotations

import math
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError, integer_types, numeric_types
from ..context import Context, current_context

__all__ = ["NDArray", "array", "_wrap_like", "waitall", "from_jax", "empty",
           "to_device"]


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class NDArray:
    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_autograd_node",
                 "_autograd_idx", "_weakref", "__weakref__")

    # class flag, overridden True by BaseSparseNDArray: lets the operator
    # hot path reject sparse dispatch with one attribute load instead of
    # an isinstance against a lazily-imported class
    _sparse_kind = False

    def __init__(self, data, ctx: Optional[Context] = None):
        self._data = data
        self._ctx = ctx
        self._grad = None
        self._grad_req = "null"
        self._autograd_node = None
        self._autograd_idx = 0
        self._weakref = None

    # ------------------------------------------------------------------ #
    # identity / metadata
    # ------------------------------------------------------------------ #
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(self._data.dtype) if not _is_tracer(self._data) \
            else self._data.dtype

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        if _is_tracer(self._data):
            return current_context()
        try:
            dev = list(self._data.devices())[0]
            if dev.platform == "cpu":
                return Context("cpu", dev.id)
            return Context("tpu", dev.id)
        except Exception:
            return current_context()

    ctx = context

    @property
    def stype(self):
        return "default"

    def __repr__(self):
        if _is_tracer(self._data):
            return f"<NDArray tracer {self._data.shape} @{self.context}>"
        return f"{onp.asarray(self._data)!r}\n<NDArray {('x'.join(map(str, self.shape)) or 'scalar')} @{self.context}>"

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(onp.asarray(self._data))

    def __float__(self):
        return float(onp.asarray(self._data))

    def __int__(self):
        return int(onp.asarray(self._data))

    def __index__(self):
        return int(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def _weak(self):
        if self._weakref is None:
            self._weakref = weakref.ref(self)
        return self._weakref

    # ------------------------------------------------------------------ #
    # engine analogs
    # ------------------------------------------------------------------ #
    def wait_to_read(self):
        """Reference ``NDArray::WaitToRead`` -> ``block_until_ready``."""
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def asnumpy(self) -> onp.ndarray:
        # XLA may expose transposed (F-order) buffers; reference asnumpy
        # always returns C-order
        return onp.ascontiguousarray(onp.asarray(self._data))

    def item(self):
        return self.asnumpy().item()

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not a scalar")
        return self.asnumpy().item()

    def tolist(self):
        return self.asnumpy().tolist()

    # jax interop -------------------------------------------------------- #
    def asjax(self):
        return self._data

    def __jax_array__(self):
        return self._data

    def __array__(self, dtype=None):
        a = onp.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    # ------------------------------------------------------------------ #
    # mutation-as-rebind
    # ------------------------------------------------------------------ #
    def _rebind(self, data, node=None, idx=0):
        self._data = data
        self._autograd_node = node
        self._autograd_idx = idx
        return self

    # ------------------------------------------------------------------ #
    # autograd surface
    # ------------------------------------------------------------------ #
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a zero gradient buffer (reference
        ``NDArray.attach_grad`` -> ``MXAutogradMarkVariables``)."""
        if grad_req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {grad_req}")
        self._grad = NDArray(jnp.zeros(self.shape, _grad_dtype(self._data.dtype)),
                             self._ctx)
        self._grad_req = grad_req
        # detach from any recorded graph: it becomes a leaf
        self._autograd_node = None
        self._autograd_idx = 0

    @property
    def grad(self):
        return self._grad

    def zero_grad(self):
        if self._grad is not None:
            self._grad._rebind(jnp.zeros_like(self._grad._data))

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        return NDArray(self._data, self._ctx)

    # ------------------------------------------------------------------ #
    # conversion / movement
    # ------------------------------------------------------------------ #
    def astype(self, dtype, copy=True):
        from ..ops import defs as _ops
        return _ops.cast(self, dtype=onp.dtype(dtype).name)

    def copy(self) -> "NDArray":
        return NDArray(jnp.asarray(self._data), self._ctx)

    # -- dlpack interchange (reference: dlpack bridge, SURVEY.md §3.1
    # "dlpack": zero-copy tensor interchange ABI) ----------------------- #
    def to_dlpack_for_read(self):
        """Export as a DLPack capsule (zero-copy where the consumer shares
        the device; reference ``to_dlpack_for_read``)."""
        return self._data.__dlpack__()

    to_dlpack_for_write = to_dlpack_for_read  # values are immutable (XLA)

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__(stream=stream) if stream is not None \
            else self._data.__dlpack__()

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def copyto(self, other):
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise MXNetError(f"copyto shape mismatch {self.shape} vs {other.shape}")
            data = self._data
            if not _is_tracer(data):
                data = jax.device_put(data, other.context.jax_device())
            other._rebind(jnp.asarray(data, other._data.dtype))
            return other
        if isinstance(other, Context):
            return self.as_in_context(other)
        raise TypeError(f"copyto: unsupported target {type(other)}")

    def as_in_context(self, ctx: Context) -> "NDArray":
        """Reference ``as_in_context``: cross-device copy via engine
        ``CopyFromTo``; here ``jax.device_put`` (async, like FnProperty
        kCopyFromGPU ops)."""
        if _is_tracer(self._data):
            return NDArray(self._data, ctx)
        return NDArray(jax.device_put(self._data, ctx.jax_device()), ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def as_np_ndarray(self):
        from ..numpy.multiarray import ndarray as np_ndarray
        out = np_ndarray(self._data, self._ctx)
        out._grad = self._grad
        out._grad_req = self._grad_req
        out._autograd_node = self._autograd_node
        out._autograd_idx = self._autograd_idx
        return out

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import tostype as _tostype
        return _tostype(self, stype)

    # ------------------------------------------------------------------ #
    # shape ops (delegate to the op registry so autograd flows)
    # ------------------------------------------------------------------ #
    def reshape(self, *shape, **kwargs):
        from ..ops import defs as _ops
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _ops.reshape(self, shape=tuple(shape))

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        from ..ops import defs as _ops
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _ops.transpose(self, axes=tuple(axes) if axes else None)

    @property
    def T(self):
        return self.transpose()

    def expand_dims(self, axis):
        from ..ops import defs as _ops
        return _ops.expand_dims(self, axis=axis)

    def squeeze(self, axis=None):
        from ..ops import defs as _ops
        return _ops.squeeze(self, axis=axis)

    def flatten(self):
        from ..ops import defs as _ops
        return _ops.flatten(self)

    def broadcast_to(self, shape):
        from ..ops import defs as _ops
        return _ops.broadcast_to(self, shape=tuple(shape))

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def swapaxes(self, dim1, dim2):
        from ..ops import defs as _ops
        return _ops.swapaxes(self, dim1=dim1, dim2=dim2)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from ..ops import defs as _ops
        return _ops.split(self, num_outputs=num_outputs, axis=axis,
                          squeeze_axis=squeeze_axis)

    def slice(self, begin, end, step=None):
        from ..ops import defs as _ops
        return _ops.slice(self, begin=tuple(begin), end=tuple(end),
                          step=tuple(step) if step else None)

    def slice_axis(self, axis, begin, end):
        from ..ops import defs as _ops
        return _ops.slice_axis(self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        from ..ops import defs as _ops
        return _ops.take(self, indices, axis=axis, mode=mode)

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        from ..ops import defs as _ops
        return _ops.one_hot(self, depth=depth, on_value=on_value,
                            off_value=off_value, dtype=dtype)

    def tile(self, reps):
        from ..ops import defs as _ops
        return _ops.tile(self, reps=tuple(reps))

    def repeat(self, repeats, axis=None):
        from ..ops import defs as _ops
        return _ops.repeat(self, repeats=repeats, axis=axis)

    def flip(self, axis):
        from ..ops import defs as _ops
        return _ops.flip(self, axis=axis)

    def pad(self, mode="constant", pad_width=None, constant_value=0):
        from ..ops import defs as _ops
        return _ops.pad(self, mode=mode, pad_width=tuple(pad_width),
                        constant_value=constant_value)

    def diag(self, k=0):
        from ..ops import defs as _ops
        return _ops.diag(self, k=k)

    # reductions --------------------------------------------------------- #
    def sum(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.sum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.mean(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.prod(self, axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.norm(self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.argmax(self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        from ..ops import defs as _ops
        return _ops.argmin(self, axis=axis, keepdims=keepdims)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        from ..ops import defs as _ops
        return _ops.topk(self, axis=axis, k=k, ret_typ=ret_typ,
                         is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        from ..ops import defs as _ops
        return _ops.sort(self, axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True, dtype="float32"):
        from ..ops import defs as _ops
        return _ops.argsort(self, axis=axis, is_ascend=is_ascend, dtype=dtype)

    # elementwise methods ------------------------------------------------ #
    def abs(self):
        from ..ops import defs as _ops
        return _ops.abs(self)

    def exp(self):
        from ..ops import defs as _ops
        return _ops.exp(self)

    def log(self):
        from ..ops import defs as _ops
        return _ops.log(self)

    def sqrt(self):
        from ..ops import defs as _ops
        return _ops.sqrt(self)

    def square(self):
        from ..ops import defs as _ops
        return _ops.square(self)

    def relu(self):
        from ..ops import defs as _ops
        return _ops.relu(self)

    def sigmoid(self):
        from ..ops import defs as _ops
        return _ops.sigmoid(self)

    def tanh(self):
        from ..ops import defs as _ops
        return _ops.tanh(self)

    def softmax(self, axis=-1):
        from ..ops import defs as _ops
        return _ops.softmax(self, axis=axis)

    def log_softmax(self, axis=-1):
        from ..ops import defs as _ops
        return _ops.log_softmax(self, axis=axis)

    def clip(self, a_min, a_max):
        from ..ops import defs as _ops
        return _ops.clip(self, a_min=a_min, a_max=a_max)

    def round(self):
        from ..ops import defs as _ops
        return _ops.round(self)

    def dot(self, other, transpose_a=False, transpose_b=False):
        from ..ops import defs as _ops
        return _ops.dot(self, other, transpose_a=transpose_a,
                        transpose_b=transpose_b)

    # ------------------------------------------------------------------ #
    # python operators
    # ------------------------------------------------------------------ #
    def _binop(self, other, name, reverse=False):
        from ..ops import defs as _ops
        if self._sparse_kind or getattr(other, "_sparse_kind", False):
            return self._binop_sparse(other, name, reverse)
        fn = getattr(_ops, name)
        if reverse:
            return fn(_coerce(other, self), self)
        return fn(self, _coerce(other, self))

    def _binop_sparse(self, other, name, reverse=False):
        """Storage-aware operator dispatch (reference: FComputeEx —
        elemwise ops keep sparse storage when both operands share it).
        Same-kind, same-shape sparse pairs route through the union
        kernels OUTSIDE autograd recording (the union kernels build
        results structurally and record no tape node); every other case
        — mixed storage, scalars, broadcasts, or under ``record()`` —
        runs the registered dense op on the operands' dense mirrors,
        which records normally (sparse operands then receive DENSE
        gradients, the reference's storage-fallback grad behavior)."""
        from .. import autograd
        from ..ops import defs as _ops
        recording = autograd.is_recording()
        # scalar scale of a sparse array keeps storage (reference
        # _mul_scalar/_div_scalar FComputeEx on row_sparse/csr): only
        # the stored values scale, the pattern — and the dense mirror's
        # memory — is never materialized.  Scalar add/sub destroys
        # sparsity, so those fall through to the dense path.  Restricted
        # to floating dtypes (an int ``a / 2`` or ``a * 0.5`` must
        # promote like the dense op, not truncate the scale factor to 0)
        # and nonzero divisors (0/0 = nan on unstored zeros — only the
        # dense path can represent that).
        if self._sparse_kind and isinstance(other, numeric_types) \
                and not recording \
                and jnp.issubdtype(jnp.dtype(self.dtype), jnp.floating) \
                and math.isfinite(float(other)):
            # non-finite scalars (and zero divisors below) must hit the
            # dense op: x * inf / x / nan poison the UNSTORED zeros too
            # (0 * inf = nan), which value-only scaling can't represent
            from . import sparse as _sparse
            if name == "broadcast_mul":
                return _sparse._scale(self, float(other))
            if name == "broadcast_div" and not reverse \
                    and float(other) != 0.0:
                return _sparse._scale(self, 1.0 / float(other))
        a, b = (other, self) if reverse else (self, other)
        a, b = _coerce(a, self), _coerce(b, self)
        spname = _SPARSE_BINOPS.get(name)
        if spname is not None and type(a) is type(b) \
                and a._sparse_kind and a.shape == b.shape \
                and not recording:
            from . import sparse as _sparse
            return _sparse._elemwise(spname, a, b)
        return getattr(_ops, name)(a, b)

    def __add__(self, o):
        return self._binop(o, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub")

    def __rsub__(self, o):
        return self._binop(o, "broadcast_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div")

    def __rtruediv__(self, o):
        return self._binop(o, "broadcast_div", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod")

    def __rmod__(self, o):
        return self._binop(o, "broadcast_mod", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __rpow__(self, o):
        return self._binop(o, "broadcast_power", reverse=True)

    def __matmul__(self, o):
        from ..ops import defs as _ops
        return _ops.matmul(self, o)

    def __neg__(self):
        from ..ops import defs as _ops
        return _ops.negative(self)

    def __abs__(self):
        return self.abs()

    def __eq__(self, o):
        return self._binop(o, "broadcast_equal")

    def __ne__(self, o):
        return self._binop(o, "broadcast_not_equal")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal")

    def __hash__(self):
        return id(self)

    # in-place: rebind (tape-visible when recording) --------------------- #
    def __iadd__(self, o):
        r = self.__add__(o)
        return self._rebind(r._data, r._autograd_node, r._autograd_idx)

    def __isub__(self, o):
        r = self.__sub__(o)
        return self._rebind(r._data, r._autograd_node, r._autograd_idx)

    def __imul__(self, o):
        r = self.__mul__(o)
        return self._rebind(r._data, r._autograd_node, r._autograd_idx)

    def __itruediv__(self, o):
        r = self.__truediv__(o)
        return self._rebind(r._data, r._autograd_node, r._autograd_idx)

    # ------------------------------------------------------------------ #
    # indexing
    # ------------------------------------------------------------------ #
    def __getitem__(self, key):
        from ..ops import defs as _ops
        key = _index_key(key)
        return _ops._index(self, key=key)

    def __setitem__(self, key, value):
        if self._autograd_node is not None:
            from .. import autograd
            if autograd.is_recording():
                raise MXNetError(
                    "in-place assignment to an array produced inside "
                    "autograd.record() is not differentiable; use "
                    "concat/where instead")
        key = _index_key(key)
        if isinstance(value, NDArray):
            value = value._data
        self._data = self._data.at[key].set(value)

    def begin_state(self, *a, **k):  # pragma: no cover
        raise AttributeError("begin_state")


def _grad_dtype(dtype):
    d = onp.dtype(dtype) if not isinstance(dtype, onp.dtype) else dtype
    try:
        if onp.issubdtype(d, onp.floating):
            return d
    except TypeError:
        return dtype  # bfloat16 etc.
    return onp.float32


def _index_key(key):
    """Normalize an index: NDArray indices -> jax arrays; tuples recurse."""
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(_index_key(k) for k in key)
    return key


# python-operator name -> sparse union-kernel name (storage-preserving
# subset; everything else takes the dense fallback in _binop_sparse)
_SPARSE_BINOPS = {"broadcast_add": "add", "broadcast_sub": "subtract",
                  "broadcast_mul": "multiply"}


def _coerce(x, like: "NDArray"):
    if isinstance(x, NDArray):
        return x
    if isinstance(x, numeric_types):
        return NDArray(jnp.asarray(x, like._data.dtype), like._ctx)
    if isinstance(x, (onp.ndarray, list, tuple)):
        return NDArray(jnp.asarray(x), like._ctx)
    raise TypeError(f"cannot coerce {type(x)} to NDArray")


def _wrap_like(data, ref: Optional[NDArray]) -> NDArray:
    # honor the ref's class so mx.np arrays propagate through every op —
    # EXCEPT sparse refs: a generic kernel's result is dense, and sparse
    # containers need structural (data+indices) construction; ops that
    # preserve sparse storage build their outputs explicitly
    cls = type(ref) if ref is not None else NDArray
    if getattr(cls, "_sparse_kind", False):
        cls = NDArray
    return cls(data, ref._ctx if ref is not None else None)


# ---------------------------------------------------------------------- #
# creation
# ---------------------------------------------------------------------- #

def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """``mx.nd.array`` — create from numpy/list/NDArray."""
    if isinstance(source, NDArray):
        data = source._data
    else:
        data = source
    if dtype is None and not isinstance(source, NDArray):
        # MXNet defaults python/np-float64 input to float32
        try:
            if onp.asarray(source).dtype == onp.float64:
                dtype = onp.float32
        except Exception:
            pass
    if ctx is not None and not isinstance(data, jax.Array):
        # non-blocking single-hop H2D: hand host memory straight to the
        # target device — ``jax.device_put`` returns immediately with the
        # copy in flight (and canonicalizes dtypes exactly like
        # ``jnp.asarray``), instead of committing to the default device
        # first and re-transferring.  This is the path the device-prefetch
        # input pipeline rides: batch k+1's copy overlaps step k.
        host = onp.asarray(data, dtype=dtype)
        return NDArray(jax.device_put(host, ctx.jax_device()), ctx)
    arr = jnp.asarray(data, dtype=dtype)
    if ctx is not None:
        arr = jax.device_put(arr, ctx.jax_device())
    return NDArray(arr, ctx)


# ---------------------------------------------------------------------- #
# device placement (the device-prefetch input pipeline's H2D stage)
# ---------------------------------------------------------------------- #

def _placement_target(device):
    """Normalize a placement spec into the one object ``jax.device_put``
    accepts: a ``Context``/``jax.Device`` resolves to that device; a
    ``jax.sharding.Sharding`` passes through; a multi-element list of
    contexts/devices becomes a batch-axis ``NamedSharding`` so ONE
    ``device_put`` lands each device's slice pre-sharded (data-parallel
    feeds with no per-replica host slicing)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec, Sharding
    if device is None:
        return None
    if isinstance(device, Context):
        return device.jax_device()
    if isinstance(device, Sharding):
        return device
    if isinstance(device, (list, tuple)):
        devs = [d.jax_device() if isinstance(d, Context) else d
                for d in device]
        if not devs:
            raise MXNetError("empty device list")
        if not all(isinstance(d, jax.Device) for d in devs):
            raise MXNetError(f"invalid device list {device!r}")
        if len(devs) == 1:
            return devs[0]
        return NamedSharding(Mesh(onp.array(devs), ("dp",)),
                             PartitionSpec("dp"))
    if isinstance(device, jax.Device):
        return device
    raise MXNetError(
        f"cannot interpret {device!r} as a Context, jax.Device, Sharding, "
        "or list of contexts/devices")


def _device_put_leaf(arr, target):
    """One async ``device_put``.  A batch whose leading dim doesn't divide
    a batch-axis sharding (e.g. the ``last_batch='keep'`` tail) is placed
    replicated on the same mesh instead — every device still holds it, and
    consumers (``split_and_load``) fall back to slicing for that batch."""
    from jax.sharding import NamedSharding, PartitionSpec
    try:
        return jax.device_put(arr, target)
    except ValueError:
        if isinstance(target, NamedSharding):
            return jax.device_put(
                arr, NamedSharding(target.mesh, PartitionSpec()))
        raise


def to_device(data, device):
    """Asynchronously place a batch on a device (or pre-sharded across
    devices).

    ``data`` may be an :class:`NDArray`, a numpy/jax array, or an
    arbitrarily nested list/tuple/dict of them (the shapes batchify
    functions produce); ``device`` accepts everything
    :func:`_placement_target` does.  Returns the same structure with every
    array leaf replaced by a device-resident :class:`NDArray` whose
    transfer is already in flight — nothing blocks (``jax.device_put`` is
    async under XLA), which is what lets the prefetch ring overlap H2D of
    batch ``k+1`` with step ``k``."""
    target = _placement_target(device)
    if target is None:
        return data
    return _place_tree(data, target)


def _place_tree(x, target):
    if isinstance(x, NDArray):
        out = _wrap_like(_device_put_leaf(x._data, target), x)
        out._ctx = None  # context now derives from the actual placement
        return out
    if isinstance(x, tuple) and hasattr(x, "_fields"):  # namedtuple
        return type(x)(*(_place_tree(v, target) for v in x))
    if isinstance(x, (list, tuple)):
        return type(x)(_place_tree(v, target) for v in x)
    if isinstance(x, dict):
        return {k: _place_tree(v, target) for k, v in x.items()}
    if isinstance(x, (onp.ndarray, jax.Array)):
        return NDArray(_device_put_leaf(x, target))
    return x


def empty(shape, ctx=None, dtype=None):
    return array(onp.zeros(shape, dtype or onp.float32), ctx=ctx)


def from_jax(x, ctx=None) -> NDArray:
    return NDArray(x, ctx)


def waitall():
    """Reference ``mx.nd.waitall`` -> block on all pending work."""
    try:
        jax.block_until_ready(jax.device_put(0))
    except Exception:
        pass
    jax.effects_barrier()
