"""Sparse storage types (row_sparse / csr) with real O(nnz) kernels.

Reference surface: ``python/mxnet/ndarray/sparse.py`` + sparse kernels in
``src/operator/tensor`` (SURVEY.md §3.1 NDArray storage types + "sparse
kernels for row_sparse/csr (dot, elemwise, sparse_retain)", §3.3 "Sparse
/ large embedding DP").

TPU-native stance (r3 upgrade over the dense-emulation classes):

- storage is **component-based**: a ``CSRNDArray`` holds device arrays
  ``(data, indices, indptr, row_ids)``; a ``RowSparseNDArray`` holds
  ``(data, indices)``.  The dense mirror is materialized **lazily**, only
  when something outside the sparse API touches ``._data`` (XLA is
  dense-only, so interop with the rest of the framework goes through the
  mirror) — constructing a sparse array no longer allocates the dense
  buffer.
- the kernels compute **from the components** at O(nnz) cost:
  ``dot(csr, dense)`` is a gather + ``segment_sum`` (one MXU-friendly
  elementwise-times-gathered-rows followed by a segmented reduction —
  the TPU-native answer to the reference's CPU/GPU csr kernels),
  ``dot(row_sparse, dense)`` is a gathered matmul + scatter,
  ``sparse_retain`` / ``retain`` are gathers over kept rows.
- structure-changing ops (csr ± csr, row_sparse ± row_sparse) union
  the pattern ON DEVICE with fixed-capacity padded kernels
  (``_csr_union_device`` / ``_rs_union_device``): static shapes,
  jittable, one trim count read back at object construction.

Gradients: the dot kernels are registered ops, so the standard vjp-based
tape (ops/registry.py) differentiates them; the backward of
``dot(csr, x)`` w.r.t. ``x`` is itself an O(nnz) segment-sum.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..ops.registry import op
from .ndarray import NDArray, array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "tostype", "retain",
           "sparse_retain", "zeros", "dot", "cast_storage", "add",
           "subtract", "multiply"]


# --------------------------------------------------------------------------- #
# registered kernels (pure jax, O(nnz)) — differentiable via the tape
# --------------------------------------------------------------------------- #

@op("_sparse_segment_dot")
def _segment_dot(data, gather_ids, segment_ids, rhs, *, num_segments):
    """out[num_segments, N] = Σ_j data[j] · rhs[gather_ids[j], :] scattered
    into row segment_ids[j] — the one kernel behind csr·dense and its
    transpose (reference csr dot kernels, SURVEY.md §3.1 sparse rows)."""
    vals = data[:, None] * rhs[gather_ids]
    return jax.ops.segment_sum(vals, segment_ids,
                               num_segments=num_segments)


@op("_sparse_rowsparse_dot")
def _rowsparse_dot(values, indices, rhs, *, num_rows):
    """dot(row_sparse, dense): gathered matmul + scatter of result rows."""
    out_rows = jnp.matmul(values, rhs)
    out = jnp.zeros((num_rows, rhs.shape[1]), out_rows.dtype)
    return out.at[indices].set(out_rows)


@op("_sparse_rowsparse_dot_t")
def _rowsparse_dot_t(values, indices, rhs, *, num_cols):
    """dot(row_sparse, dense, transpose_a=True): lhsᵀ·rhs =
    valuesᵀ · rhs[indices] — O(nnz_rows) gather, dense matmul."""
    del num_cols
    return jnp.matmul(values.T, rhs[indices])


# --------------------------------------------------------------------------- #
# shared host-side helpers (scipy has no bf16 — round-trip through f32,
# values are cast back to the array's dtype by the callers)
# --------------------------------------------------------------------------- #

def _np_f32(x):
    a = onp.asarray(x)
    return a.astype(onp.float32) if a.dtype.name == "bfloat16" else a


def _dense_to_scipy_csr(dense):
    import scipy.sparse as sp
    return sp.csr_matrix(_np_f32(dense))


def _dense_to_rs(dense):
    """(nonzero row indices, those rows) of a dense array."""
    a = onp.asarray(dense)
    nz = onp.where(onp.any(a.reshape(a.shape[0], -1) != 0, axis=1))[0]
    return nz, a[nz]


def _rowids_of(indptr):
    ip = onp.asarray(indptr, onp.int64)
    return jnp.asarray(onp.repeat(
        onp.arange(len(ip) - 1, dtype=onp.int32), onp.diff(ip)))


# --------------------------------------------------------------------------- #
# NDArray subclasses with lazy dense mirrors
# --------------------------------------------------------------------------- #

class BaseSparseNDArray(NDArray):
    """Component storage + lazy dense mirror.  ``_data`` is a property:
    reading it materializes (and caches) the dense array; writing it (e.g.
    an in-place rebind from autograd) stores the dense value and marks the
    components stale, after which component accessors re-derive from the
    mirror."""

    __slots__ = ("_sp_shape", "_sp_dtype", "_dense_cache", "_stale")

    _sparse_kind = True  # see NDArray._sparse_kind

    def _init_base(self, shape, dtype, ctx):
        self._sp_shape = tuple(int(s) for s in shape)
        self._sp_dtype = jnp.dtype(dtype)
        self._dense_cache = None
        self._stale = False
        super().__init__(None, ctx)

    # -- lazy mirror ---------------------------------------------------- #
    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._to_dense()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        self._dense_cache = value
        if value is not None:
            self._stale = True  # components no longer describe the value

    @property
    def shape(self):
        return self._sp_shape

    @property
    def dtype(self):
        return onp.dtype(self._sp_dtype)

    def _to_dense(self):
        raise NotImplementedError

    def _refresh(self):
        """Recompute components from the dense mirror after a rebind."""
        raise NotImplementedError

    def _components(self):
        if self._stale:
            self._refresh()
            self._stale = False
        return None


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair; dense shape known; values on device."""

    __slots__ = ("_rs_data", "_rs_indices")

    def __init__(self, data, indices, shape, ctx=None):
        data = jnp.asarray(data)
        self._init_base(shape, data.dtype, ctx)
        self._rs_data = data
        self._rs_indices = jnp.asarray(indices, jnp.int64) \
            if jnp.asarray(indices).dtype == jnp.int64 \
            else jnp.asarray(indices, jnp.int32)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        self._components()
        return NDArray(self._rs_indices)

    @property
    def data(self):
        self._components()
        return NDArray(self._rs_data)

    def _to_dense(self):
        return jnp.zeros(self._sp_shape, self._sp_dtype).at[
            self._rs_indices].set(self._rs_data)

    def _refresh(self):
        nz, rows = _dense_to_rs(self._dense_cache)
        self._rs_indices = jnp.asarray(nz, jnp.int32)
        self._rs_data = jnp.asarray(rows).astype(self._sp_dtype)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "row_sparse":
            return self
        raise MXNetError(f"unsupported stype {stype}")

    def copyto(self, other):
        return NDArray(self._data).copyto(other)


class CSRNDArray(BaseSparseNDArray):
    """(data, indices, indptr) CSR triple; ``row_ids`` (the expanded row
    index per nonzero) is precomputed once at construction so every dot is
    a pure static-shape device kernel."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr", "_csr_rowids")

    def __init__(self, data, indptr, indices, shape, ctx=None):
        data = jnp.asarray(data)
        self._init_base(shape, data.dtype, ctx)
        ip = onp.asarray(indptr, onp.int64)
        self._csr_data = data
        self._csr_indices = jnp.asarray(indices, jnp.int32)
        self._csr_indptr = jnp.asarray(ip)
        self._csr_rowids = _rowids_of(ip)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        self._components()
        return NDArray(self._csr_data)

    @property
    def indices(self):
        self._components()
        return NDArray(self._csr_indices)

    @property
    def indptr(self):
        self._components()
        return NDArray(self._csr_indptr)

    def _to_dense(self):
        m, _n = self._sp_shape
        out = jnp.zeros(self._sp_shape, self._sp_dtype)
        return out.at[self._csr_rowids, self._csr_indices].set(
            self._csr_data)

    def _refresh(self):
        m = _dense_to_scipy_csr(self._dense_cache)
        self._csr_data = jnp.asarray(m.data).astype(self._sp_dtype)
        self._csr_indices = jnp.asarray(m.indices, jnp.int32)
        self._csr_indptr = jnp.asarray(m.indptr, onp.int64)
        self._csr_rowids = _rowids_of(m.indptr)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "csr":
            return self
        raise MXNetError(f"unsupported stype {stype}")

    def _scipy(self):
        import scipy.sparse as sp
        self._components()
        return sp.csr_matrix(
            (_np_f32(self._csr_data), onp.asarray(self._csr_indices),
             onp.asarray(self._csr_indptr)), shape=self._sp_shape)


# --------------------------------------------------------------------------- #
# constructors
# --------------------------------------------------------------------------- #

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(jnp.asarray(data, dtype), indices, shape,
                                ctx)
    dense = array(arg1, ctx=ctx, dtype=dtype)
    return tostype(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(data, dtype), indptr, indices, shape,
                          ctx)
    if isinstance(arg1, NDArray) or hasattr(arg1, "__array__"):
        dense = array(arg1, ctx=ctx, dtype=dtype)
        return tostype(dense, "csr")
    raise MXNetError("csr_matrix: pass (data, indices, indptr) or a dense "
                     "array")


def tostype(nd: NDArray, stype: str):
    if stype == "default":
        return NDArray(nd._data, nd._ctx)
    if stype == "row_sparse":
        nz, rows = _dense_to_rs(nd._data)
        return RowSparseNDArray(rows, nz, tuple(nd.shape))
    if stype == "csr":
        m = _dense_to_scipy_csr(nd._data)
        return CSRNDArray(jnp.asarray(m.data).astype(nd.dtype),
                          m.indptr, m.indices, m.shape)
    raise MXNetError(f"unknown stype {stype}")


def cast_storage(nd: NDArray, stype: str):
    """Reference anchor ``cast_storage``: convert between storage types."""
    if isinstance(nd, BaseSparseNDArray):
        return nd.tostype(stype)
    return tostype(nd, stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """``mx.nd.sparse.zeros('row_sparse', shape)`` (reference surface)."""
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]),
                                          jnp.dtype(dtype)),
                                jnp.zeros((0,), jnp.int32), shape, ctx)
    if stype == "csr":
        return CSRNDArray(onp.zeros((0,), dtype),
                          onp.zeros(shape[0] + 1, onp.int64),
                          onp.zeros((0,), onp.int64), shape, ctx)
    raise MXNetError(f"unknown stype {stype}")


# --------------------------------------------------------------------------- #
# kernels' NDArray-level surface
# --------------------------------------------------------------------------- #

def retain(rs: RowSparseNDArray, indices):
    """Keep only the listed rows (reference ``sparse_retain``): the
    membership test, stable packing of surviving rows, and the value
    gather all run as one static-shape device computation; only the
    final trim count reads back (same discipline as ``_rs_elemwise``)."""
    if rs.shape[0] >= 2 ** 31 - 1:
        raise MXNetError(
            "sparse_retain: >= 2^31-1 rows — int32 row indices would "
            "overflow (enable a chunked path if this arises)")
    idx = jnp.asarray(indices._data if isinstance(indices, NDArray)
                      else jnp.asarray(indices), jnp.int32)
    rs._components()
    rows = jnp.asarray(rs._rs_indices, jnp.int32)
    n = rows.shape[0]
    keep = jnp.isin(rows, idx)
    # stable pack: survivors first, original (sorted-row) order kept
    order = jnp.argsort(jnp.where(keep, jnp.arange(n), n + jnp.arange(n)))
    packed_rows = rows[order]
    packed_vals = rs._rs_data[order]
    cnt = int(keep.sum())                      # the one host scalar
    return RowSparseNDArray(packed_vals[:cnt], packed_rows[:cnt], rs.shape)


def sparse_retain(data, indices):
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("sparse_retain expects a RowSparseNDArray")
    return retain(data, indices)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """``mx.nd.sparse.dot`` — csr/row_sparse × dense at O(nnz) cost.

    csr·dense and csrᵀ·dense run the ``_sparse_segment_dot`` kernel
    (gather + segment_sum); row_sparse·dense runs a gathered matmul.
    Dense×dense falls through to the dense op.  Gradients w.r.t. the
    dense operand flow through the registered kernels."""
    from . import dot as _dense_dot

    if not isinstance(lhs, BaseSparseNDArray):
        return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                          transpose_b=transpose_b)
    rhs_d = NDArray(rhs._data) if isinstance(rhs, BaseSparseNDArray) else rhs
    if transpose_b:
        rhs_d = NDArray(jnp.swapaxes(rhs_d._data, -1, -2))

    if isinstance(lhs, CSRNDArray):
        lhs._components()
        m, k = lhs.shape
        if transpose_a:
            return _segment_dot(NDArray(lhs._csr_data),
                                NDArray(lhs._csr_rowids),
                                NDArray(lhs._csr_indices), rhs_d,
                                num_segments=k)
        return _segment_dot(NDArray(lhs._csr_data),
                            NDArray(lhs._csr_indices),
                            NDArray(lhs._csr_rowids), rhs_d,
                            num_segments=m)
    if isinstance(lhs, RowSparseNDArray):
        lhs._components()
        if transpose_a:
            return _rowsparse_dot_t(NDArray(lhs._rs_data),
                                    NDArray(lhs._rs_indices), rhs_d,
                                    num_cols=lhs.shape[1])
        return _rowsparse_dot(NDArray(lhs._rs_data),
                              NDArray(lhs._rs_indices), rhs_d,
                              num_rows=lhs.shape[0])
    raise MXNetError(f"sparse.dot: unsupported lhs type {type(lhs)}")


_KEY_SENTINEL = onp.iinfo(onp.int32).max


def _csr_union_device(keys_a, vals_a, keys_b, vals_b, mode: str):
    """Fixed-capacity (padded-nnz) CSR pattern union/intersection,
    ENTIRELY in jax (VERDICT r3 item 6 — replaces the host-scipy union).

    Inputs: flattened int32 keys (row·ncols + col, each operand's keys
    unique) and f32-compatible values.  Output capacity is the static
    ``nnz_a + nnz_b``; returns ``(keys, vals, valid)`` with the live
    entries key-sorted and packed first, dead slots keyed
    ``_KEY_SENTINEL``.  ``mode``: ``"sum"`` (union; subtract = negate
    vals_b first) or ``"prod"`` (intersection — multiply's pattern).
    Jittable: static shapes, no host round-trip.
    """
    cap = keys_a.shape[0] + keys_b.shape[0]
    keys = jnp.concatenate([keys_a, keys_b])
    vals = jnp.concatenate([vals_a, vals_b]).astype(jnp.float32)
    order = jnp.argsort(keys)
    k = keys[order]
    v = vals[order]
    if mode == "sum":
        is_new = jnp.concatenate(
            [jnp.ones((1,), bool), k[1:] != k[:-1]]) if cap else \
            jnp.ones((0,), bool)
        seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        out_keys = jnp.full((cap,), _KEY_SENTINEL, jnp.int32).at[seg].set(k)
        out_vals = jax.ops.segment_sum(v, seg, num_segments=cap)
    elif mode == "prod":
        # each key appears 1-2 times; pairs are the intersection
        nxt_same = jnp.concatenate(
            [k[1:] == k[:-1], jnp.zeros((1,), bool)]) if cap else \
            jnp.zeros((0,), bool)
        prod = v * jnp.concatenate([v[1:], jnp.zeros((1,), jnp.float32)]) \
            if cap else v
        out_keys = jnp.where(nxt_same, k, _KEY_SENTINEL)
        out_vals = jnp.where(nxt_same, prod, 0.0)
    else:
        raise MXNetError(f"unknown union mode {mode}")
    # prune explicit zeros (cancellations, zero products) like the scipy/
    # reference csr binops do — callers observe nnz, so keeping them would
    # be a visible pattern regression; one stable resort packs live
    # entries first in key order
    out_keys = jnp.where(out_vals == 0.0, _KEY_SENTINEL, out_keys)
    order2 = jnp.argsort(out_keys)
    out_keys = out_keys[order2]
    out_vals = out_vals[order2]
    return out_keys, out_vals, out_keys != _KEY_SENTINEL


def _csr_elemwise(opname, a: CSRNDArray, b: CSRNDArray):
    """Structure-changing csr elemwise.  The pattern union/intersection
    and the value math run as ONE static-shape device kernel
    (``_csr_union_device``); only the final trim to the true nnz (a CSR
    object-construction concern) reads one count back to the host."""
    if a.shape != b.shape:
        raise MXNetError(f"csr elemwise {opname}: shape mismatch "
                         f"{a.shape} vs {b.shape}")
    nrows, ncols = a.shape
    if nrows * ncols >= 2 ** 31 - 1:
        raise MXNetError(
            "csr elemwise: matrix has >= 2^31 cells — int32 union keys "
            "would overflow (enable a chunked path if this arises)")
    if opname not in ("add", "subtract", "multiply"):
        raise MXNetError(f"unsupported csr elemwise {opname}")
    a._components()
    b._components()
    ka = a._csr_rowids.astype(jnp.int32) * ncols + a._csr_indices
    kb = b._csr_rowids.astype(jnp.int32) * ncols + b._csr_indices
    va = a._csr_data
    vb = b._csr_data if opname != "subtract" else -b._csr_data
    mode = "prod" if opname == "multiply" else "sum"
    keys, vals, valid = _csr_union_device(ka, va, kb, vb, mode)
    n = int(valid.sum())                       # the one host scalar
    keys_h = onp.asarray(keys[:n])
    rows = keys_h // ncols
    cols = keys_h % ncols
    indptr = onp.zeros(nrows + 1, onp.int64)
    indptr[1:] = onp.cumsum(onp.bincount(rows, minlength=nrows))
    return CSRNDArray(vals[:n].astype(a._sp_dtype), indptr, cols, a.shape)


def _rs_union_device(keys_a, vals_a, keys_b, vals_b, opname: str):
    """Fixed-capacity (padded-row) row_sparse pattern union ENTIRELY in
    jax — the row_sparse sibling of ``_csr_union_device`` (VERDICT r4
    item 5: this was the last host round-trip in the sparse hot path).

    Inputs: int32 row keys (each operand's keys unique) and row-block
    values ``(nnz, *cols)``.  Output capacity is the static
    ``nnz_a + nnz_b``; returns ``(keys, vals, valid)`` with live rows
    key-sorted and packed first, dead slots keyed ``_KEY_SENTINEL``.
    All three ops keep the UNION pattern (reference row_sparse binop
    semantics: a row present in either operand stays in the result, so
    multiply yields zero rows outside the intersection — no value-based
    pruning).  Jittable: static shapes, no host round-trip."""
    na = keys_a.shape[0]
    cap = na + keys_b.shape[0]
    cols = vals_a.shape[1:]
    keys = jnp.concatenate([keys_a.astype(jnp.int32),
                            keys_b.astype(jnp.int32)])
    order = jnp.argsort(keys)
    k = keys[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), bool), k[1:] != k[:-1]]) if cap else \
        jnp.ones((0,), bool)
    seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    out_keys = jnp.full((cap,), _KEY_SENTINEL, jnp.int32).at[seg].set(k)
    # packed union slot of each ORIGINAL entry: invert the sort
    inv = jnp.argsort(order)
    slot = seg[inv]
    dt = jnp.promote_types(vals_a.dtype, vals_b.dtype)
    va = jnp.zeros((cap,) + cols, dt).at[slot[:na]].set(vals_a)
    vb = jnp.zeros((cap,) + cols, dt).at[slot[na:]].set(vals_b)
    if opname == "add":
        vals = va + vb
    elif opname == "subtract":
        vals = va - vb
    elif opname == "multiply":
        vals = va * vb
    else:
        raise MXNetError(f"unsupported row_sparse elemwise {opname}")
    return out_keys, vals, out_keys != _KEY_SENTINEL


def _rs_elemwise(opname, a: RowSparseNDArray, b: RowSparseNDArray):
    """row_sparse elemwise: pattern union AND value math as one
    static-shape device kernel (``_rs_union_device``); only the final
    trim to the true row count (an object-construction concern, same as
    the csr path) reads one count back to the host."""
    if a.shape != b.shape:
        raise MXNetError(f"row_sparse elemwise {opname}: shape mismatch "
                         f"{a.shape} vs {b.shape}")
    if a.shape[0] >= 2 ** 31 - 1:
        # row ids run to shape[0]-1: beyond this the int32 narrowing
        # wraps and a live row id would collide with _KEY_SENTINEL
        # (same guard as _csr_elemwise's cell-count check)
        raise MXNetError(
            "row_sparse elemwise: >= 2^31-1 rows — int32 row keys would "
            "overflow (enable a chunked path if this arises)")
    a._components()
    b._components()
    keys, vals, valid = _rs_union_device(
        jnp.asarray(a._rs_indices, jnp.int32), a._rs_data,
        jnp.asarray(b._rs_indices, jnp.int32), b._rs_data, opname)
    n = int(valid.sum())                       # the one host scalar
    return RowSparseNDArray(vals[:n], keys[:n], a.shape)


def _scale(x, v: float):
    """Storage-preserving scalar scale (reference ``_mul_scalar``
    FComputeEx on sparse storage): scales only the stored values —
    the pattern is untouched and the dense mirror is NEVER
    materialized (the point of sparse storage for e.g. ``grad * lr``
    on a (vocab, dim) row_sparse gradient)."""
    if isinstance(x, RowSparseNDArray):
        x._components()
        return RowSparseNDArray(x._rs_data * x._rs_data.dtype.type(v),
                                x._rs_indices, x.shape, x._ctx)
    if isinstance(x, CSRNDArray):
        x._components()
        out = CSRNDArray.__new__(CSRNDArray)
        out._init_base(x.shape, x._sp_dtype, x._ctx)
        out._csr_data = x._csr_data * x._csr_data.dtype.type(v)
        out._csr_indices = x._csr_indices
        out._csr_indptr = x._csr_indptr
        out._csr_rowids = x._csr_rowids
        return out
    raise MXNetError(f"_scale: unsupported storage {type(x).__name__}")


def _elemwise(opname, a, b):
    if isinstance(a, CSRNDArray) and isinstance(b, CSRNDArray):
        return _csr_elemwise(opname, a, b)
    if isinstance(a, RowSparseNDArray) and isinstance(b, RowSparseNDArray):
        return _rs_elemwise(opname, a, b)
    # mixed / dense operand: dense result (reference behavior)
    ad = a._data if isinstance(a, NDArray) else jnp.asarray(a)
    bd = b._data if isinstance(b, NDArray) else jnp.asarray(b)
    fn = {"add": jnp.add, "subtract": jnp.subtract,
          "multiply": jnp.multiply}[opname]
    return NDArray(fn(ad, bd))


def add(a, b):
    return _elemwise("add", a, b)


def subtract(a, b):
    return _elemwise("subtract", a, b)


def multiply(a, b):
    return _elemwise("multiply", a, b)
