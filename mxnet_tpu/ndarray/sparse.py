"""Sparse storage types (row_sparse / csr).

Reference surface: ``python/mxnet/ndarray/sparse.py`` + sparse kernels in
``src/operator/tensor`` (SURVEY.md §3.1 NDArray storage types, §3.3 "Sparse
/ large embedding DP").

TPU-native stance: XLA is dense-only; ``row_sparse`` is represented as
(indices, values) pairs materialized to dense on op boundaries, which keeps
the API (``tostype``, ``row_sparse_array``, ``retain``) working while the
performant path is sharded dense embedding tables + gather (see
parallel/).  This mirrors SURVEY.md §7 Phase 5 "row_sparse emulation +
documented descopes"."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from .ndarray import NDArray, array


class BaseSparseNDArray(NDArray):
    pass


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, data) pair; dense shape known."""

    def __init__(self, data, indices, shape, ctx=None):
        dense = jnp.zeros(shape, data.dtype).at[
            jnp.asarray(indices, jnp.int32)].set(jnp.asarray(data))
        super().__init__(dense, ctx)
        self._rs_data = jnp.asarray(data)
        self._rs_indices = jnp.asarray(indices, jnp.int32)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        return NDArray(self._rs_indices)

    @property
    def data(self):
        return NDArray(self._rs_data)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        if stype == "row_sparse":
            return self
        raise MXNetError(f"unsupported stype {stype}")


class CSRNDArray(BaseSparseNDArray):
    def __init__(self, data, indptr, indices, shape, ctx=None):
        dense = onp.zeros(shape, onp.asarray(data).dtype)
        d, ip, ix = map(onp.asarray, (data, indptr, indices))
        for r in range(shape[0]):
            for j in range(ip[r], ip[r + 1]):
                dense[r, ix[j]] = d[j]
        super().__init__(jnp.asarray(dense), ctx)

    @property
    def stype(self):
        return "csr"

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        return self


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(jnp.asarray(data, dtype), indices, shape, ctx)
    dense = array(arg1, ctx=ctx, dtype=dtype)
    return tostype(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(data, dtype), indptr, indices, shape, ctx)
    raise MXNetError("csr_matrix: pass (data, indices, indptr)")


def tostype(nd: NDArray, stype: str):
    if stype == "default":
        return NDArray(nd._data, nd._ctx)
    if stype == "row_sparse":
        dense = onp.asarray(nd._data)
        nz = onp.where(onp.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(dense[nz], nz, dense.shape)
    if stype == "csr":
        import scipy.sparse as sp  # available via numpy stack
        m = sp.csr_matrix(onp.asarray(nd._data))
        return CSRNDArray(m.data, m.indptr, m.indices, m.shape)
    raise MXNetError(f"unknown stype {stype}")


def retain(rs: RowSparseNDArray, indices):
    idx = onp.asarray(indices._data if isinstance(indices, NDArray) else indices,
                      onp.int32)
    keep = onp.isin(onp.asarray(rs._rs_indices), idx)
    return RowSparseNDArray(onp.asarray(rs._rs_data)[keep],
                            onp.asarray(rs._rs_indices)[keep], rs.shape)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """``mx.nd.sparse.zeros('row_sparse', shape)`` (reference surface)."""
    import jax.numpy as _jnp
    if stype == "row_sparse":
        return RowSparseNDArray(_jnp.zeros((0,) + tuple(shape[1:]),
                                           _jnp.dtype(dtype)),
                                _jnp.zeros((0,), _jnp.int32), shape, ctx)
    if stype == "csr":
        return CSRNDArray(onp.zeros((0,), dtype), onp.zeros(shape[0] + 1,
                                                            onp.int64),
                          onp.zeros((0,), onp.int64), shape, ctx)
    raise MXNetError(f"unknown stype {stype}")


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """``mx.nd.sparse.dot`` — csr/row_sparse × dense matmul.  Dense
    compute under the hood (XLA; PARITY.md sparse row), sparse-typed API."""
    from . import dot as _dense_dot
    a = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return _dense_dot(a, b, transpose_a=transpose_a, transpose_b=transpose_b)


def sparse_retain(data, indices):
    """Reference anchor ``sparse_retain`` op: keep only the listed rows."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("sparse_retain expects a RowSparseNDArray")
    return retain(data, indices)


__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "tostype", "retain",
           "sparse_retain", "zeros", "dot"]
