"""NDArray binary serialization — the ``.params`` file format.

Reference surface: ``NDArray::Save/Load`` in ``src/ndarray/ndarray.cc``
(SURVEY.md §5.4a: "magic-tagged list/dict of tensors; this underlies
``.params`` files").  Layout implemented here (from the public apache/mxnet
format; the reference tree was empty at survey time, so cross-loading with
actual reference files is best-effort — see PARITY.md):

  file := uint64 kMXAPINDArrayListMagic(0x112) | uint64 reserved(0)
        | uint64 n_arrays | n * ndarray_blob
        | uint64 n_names  | n * (uint64 len | bytes)  (names; 0 for list)
  ndarray_blob := uint32 NDARRAY_V2_MAGIC(0xF993FAC9) | int32 stype(0 dense)
        | uint32 ndim | int64 dims[ndim]
        | int32 devtype | int32 devid | int32 type_flag | raw data
"""
from __future__ import annotations

import struct

import numpy as onp

from ..base import MXNetError, dtype_np_to_mx, dtype_mx_to_np
from .ndarray import NDArray, array

_LIST_MAGIC = 0x112
_ND_MAGIC = 0xF993FAC9


def _write_nd(f, nd: NDArray):
    data = onp.ascontiguousarray(nd.asnumpy())
    f.write(struct.pack("<I", _ND_MAGIC))
    f.write(struct.pack("<i", 0))  # stype: kDefaultStorage (dense)
    f.write(struct.pack("<I", data.ndim))
    for d in data.shape:
        f.write(struct.pack("<q", d))
    f.write(struct.pack("<ii", 1, 0))  # saved context: cpu(0)
    f.write(struct.pack("<i", dtype_np_to_mx(data.dtype)))
    f.write(data.tobytes())


def _read_nd(f) -> NDArray:
    magic, = struct.unpack("<I", f.read(4))
    if magic != _ND_MAGIC:
        raise MXNetError(f"bad ndarray magic {magic:#x}")
    stype, = struct.unpack("<i", f.read(4))
    # 0 = kDefaultStorage (dense); -1 accepted for files written by the
    # round-1 serializer which used -1 for dense.
    if stype not in (0, -1):
        raise MXNetError(
            f"sparse .params load not supported (stype={stype}: "
            "1=row_sparse, 2=csr)")
    ndim, = struct.unpack("<I", f.read(4))
    shape = struct.unpack(f"<{ndim}q", f.read(8 * ndim)) if ndim else ()
    _devt, _devid = struct.unpack("<ii", f.read(8))
    tf, = struct.unpack("<i", f.read(4))
    dtype = dtype_mx_to_np(tf)
    n = 1
    for d in shape:
        n *= d
    buf = f.read(n * onp.dtype(dtype).itemsize)
    arr = onp.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    import jax as _jax
    if (onp.dtype(dtype) in (onp.int64, onp.uint64, onp.float64)
            and not _jax.config.jax_enable_x64):
        # jax (x64 disabled) demotes 64-bit dtypes to 32-bit.  Demote only
        # when the values survive exactly; otherwise fail loudly instead
        # of silently truncating (e.g. reference int64 large-tensor files).
        # With jax_enable_x64 on, the 64-bit array passes through unchanged.
        narrow = {onp.dtype(onp.int64): onp.int32,
                  onp.dtype(onp.uint64): onp.uint32,
                  onp.dtype(onp.float64): onp.float32}[onp.dtype(dtype)]
        demoted = arr.astype(narrow)
        if not onp.array_equal(demoted.astype(dtype), arr,
                               equal_nan=onp.dtype(dtype).kind == "f"):
            raise MXNetError(
                f"load: {onp.dtype(dtype).name} array does not fit "
                f"{onp.dtype(narrow).name} exactly and jax x64 is "
                "disabled; enable jax_enable_x64 to load this file")
        arr = demoted
    return array(arr)


def save(fname: str, data):
    """``mx.nd.save(fname, list_or_dict_of_NDArray)``."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        raise MXNetError("save: need NDArray, list, or dict")
    for a in arrays:
        if not isinstance(a, NDArray):
            raise MXNetError("save: all values must be NDArray")
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_nd(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname: str):
    with open(fname, "rb") as f:
        magic, _res = struct.unpack("<QQ", f.read(16))
        if magic != _LIST_MAGIC:
            raise MXNetError(f"bad file magic {magic:#x}")
        n, = struct.unpack("<Q", f.read(8))
        arrays = [_read_nd(f) for _ in range(n)]
        n_names, = struct.unpack("<Q", f.read(8))
        if n_names == 0:
            return arrays
        names = []
        for _ in range(n_names):
            ln, = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode())
        return dict(zip(names, arrays))
