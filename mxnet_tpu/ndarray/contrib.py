"""``mx.nd.contrib`` — the reference's contrib-op namespace
(``python/mxnet/ndarray/contrib.py``): friendly names over the registered
``_contrib_*`` ops (SURVEY.md §3.1 contrib family)."""
from __future__ import annotations

from . import (boolean_mask, _contrib_quantize_v2 as quantize_v2,
               _contrib_dequantize as dequantize,
               _contrib_requantize as requantize,
               _contrib_interleaved_matmul_selfatt_qk as
               interleaved_matmul_selfatt_qk,
               _contrib_interleaved_matmul_selfatt_valatt as
               interleaved_matmul_selfatt_valatt,
               BilinearResize2D, ROIAlign, box_nms)
from . import all_finite, multi_all_finite

__all__ = ["boolean_mask", "quantize_v2", "dequantize", "requantize",
           "interleaved_matmul_selfatt_qk",
           "interleaved_matmul_selfatt_valatt", "BilinearResize2D",
           "ROIAlign", "box_nms", "all_finite", "multi_all_finite"]
