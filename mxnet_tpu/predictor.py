"""Standalone inference runner — the reference ``c_predict_api`` answer.

Reference surface (SURVEY.md §3.1 "C API" row, ``src/c_api/c_predict_api.cc``):
``MXPredCreate(symbol_json, param_bytes) / SetInput / Forward / GetOutput``
— load an exported graph + weights and run inference with no training
machinery.  TPU-native design: the exported ``-symbol.json`` +
``-0000.params`` pair loads into a jitted forward; ``export_compiled``
additionally serializes the XLA executable itself via ``jax.export`` so a
serving process can run AOT without retracing Python model code (the
deployment role the reference's C ABI played).

    pred = Predictor("model-symbol.json", "model-0000.params",
                     {"data": (1, 3, 224, 224)})
    out = pred.forward(data=batch)[0]
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as onp

__all__ = ["Predictor"]


class Predictor:
    """Load an exported (graph-json, params) pair and run jitted inference.

    API mirrors the reference's C predict surface: ``set_input`` +
    ``forward`` + ``get_output`` (and a one-call ``forward(**inputs)``).
    """

    def __init__(self, symbol_file: str, param_file: Optional[str],
                 input_shapes: Dict[str, tuple], ctx=None,
                 dtype="float32"):
        import jax

        from . import context as _ctx
        from .gluon.block import SymbolBlock
        from .ndarray.ndarray import NDArray, array

        self._ctx = ctx or _ctx.current_context()
        self._input_names = list(input_shapes.keys())
        self._input_shapes = dict(input_shapes)
        self._dtype = dtype
        self._net = SymbolBlock.imports(symbol_file, self._input_names,
                                        param_file, ctx=self._ctx)
        self._inputs: Dict[str, NDArray] = {}
        self._outputs: List[NDArray] = []
        self._array = array

        def fwd(*xs):
            from . import autograd
            with autograd.pause(train_mode=False):
                out = self._net(*[NDArray(x) for x in xs])
            if not isinstance(out, (list, tuple)):
                out = [out]
            return [o._data for o in out]

        self._fwd = jax.jit(fwd)

    # -- reference-shaped API (MXPredSetInput / Forward / GetOutput) ------- #
    def set_input(self, name: str, data) -> None:
        if name not in self._input_names:
            raise KeyError(f"unknown input {name!r}; have "
                           f"{self._input_names}")
        self._inputs[name] = self._array(onp.asarray(data))

    def run(self) -> None:
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise ValueError(f"inputs not set: {missing}")
        outs = self._fwd(*[self._inputs[n]._data
                           for n in self._input_names])
        from .ndarray.ndarray import NDArray
        self._outputs = [NDArray(o) for o in outs]

    def get_output(self, index: int = 0):
        return self._outputs[index]

    @property
    def num_outputs(self) -> int:
        return len(self._outputs)

    # -- one-call convenience ---------------------------------------------- #
    def forward(self, **inputs):
        for name, data in inputs.items():
            self.set_input(name, data)
        self.run()
        return list(self._outputs)

    # -- AOT: serialize the compiled executable (jax.export) --------------- #
    def export_compiled(self, path: str) -> None:
        """Serialize the jitted forward as a portable StableHLO artifact
        (``jax.export``): a serving host can ``load_compiled`` and run it
        without this framework's Python model code — the deployment story
        the reference's ``c_predict_api`` ABI provided."""
        import jax
        from jax import export as jexport
        import jax.numpy as jnp

        args = [jax.ShapeDtypeStruct(self._input_shapes[n],
                                     jnp.dtype(self._dtype))
                for n in self._input_names]
        exported = jexport.export(self._fwd)(*args)
        with open(path, "wb") as f:
            f.write(exported.serialize())

    @staticmethod
    def load_compiled(path: str):
        """Returns a callable running the serialized executable; takes the
        original positional inputs (numpy or jax arrays)."""
        from jax import export as jexport

        with open(path, "rb") as f:
            exported = jexport.deserialize(f.read())

        def run(*xs):
            return exported.call(*xs)

        return run
