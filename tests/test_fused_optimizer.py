"""Fused multi-tensor optimizer apply (Optimizer.multi_update).

Parity: the fused per-group jitted apply must match the legacy per-param
loop (reachable via MXNET_FUSED_OPTIMIZER=0) across the whole optimizer
registry — including multi-precision bf16+fp32-master, per-param
lr_mult/wd_mult asymmetry, clip_gradient, and the sparse-grad fallback.
f32 math is identical up to the f32-vs-f64 rounding of scalar
precomputations (e.g. beta**t), so comparisons use tight allclose rather
than bit equality; raw bf16 params additionally see the traced-f32
lr promotion (documented in Optimizer._build_fused_apply) and get a
bf16-scale tolerance.

Dispatch-count regression: a >=50-parameter Trainer.step must issue
O(#groups) jitted apply calls, not O(#params).
"""
import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import optimizer as opt_mod
from mxnet_tpu.gluon import Parameter
from mxnet_tpu.ndarray.ndarray import NDArray
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.optimizer.optimizer import (_REGISTRY, apply_counters,
                                           reset_apply_counters)

SHAPES = [(4, 5), (7,), (2, 3, 4)]

MOMENTUM_OPTS = {"sgd", "nag", "signum", "dcasgd", "lars"}


def _mk(name, **extra):
    kw = {"learning_rate": 0.05, "wd": 0.01, "rescale_grad": 0.5}
    if name in MOMENTUM_OPTS:
        kw["momentum"] = 0.9
    kw.update(extra)
    return opt_mod.create(name, **kw)


def _mk_tensors(dtype=onp.float32, seed=0, shapes=SHAPES):
    rng = onp.random.RandomState(seed)
    wnp = [rng.randn(*s).astype(dtype) for s in shapes]
    gnp = [rng.randn(*s).astype(dtype) for s in shapes]
    return wnp, gnp


def _run_steps(opt, wnp, gnp, steps=3, mp=False, grads=None):
    ws = [NDArray(jnp.array(w)) for w in wnp]
    gs = grads if grads is not None \
        else [NDArray(jnp.array(g)) for g in gnp]
    idxs = list(range(len(ws)))
    mk_state = opt.create_state_multi_precision if mp else opt.create_state
    ss = [mk_state(i, w) for i, w in zip(idxs, ws)]
    for _ in range(steps):
        ss = opt.multi_update(idxs, ws, gs, ss)
    return ws, ss


def _assert_close(ws_f, ws_l, name, rtol=2e-5, atol=1e-5):
    # atol floor: traced-int step counts make beta**t f32 where the
    # legacy loop precomputes it in python f64 — near-zero weight
    # elements see the difference amplified to a few 1e-6 absolute
    for i, (a, b) in enumerate(zip(ws_f, ws_l)):
        onp.testing.assert_allclose(
            onp.asarray(a._data), onp.asarray(b._data),
            rtol=rtol, atol=atol,
            err_msg=f"{name} param {i}: fused != legacy")


FUSABLE = sorted(k for k, v in _REGISTRY.items() if v._fusable)


@pytest.mark.parametrize("name", FUSABLE)
def test_fused_matches_legacy_all_optimizers(name, monkeypatch):
    wnp, gnp = _mk_tensors()
    ws_f, _ = _run_steps(_mk(name), wnp, gnp)
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    ws_l, _ = _run_steps(_mk(name), wnp, gnp)
    _assert_close(ws_f, ws_l, name)


@pytest.mark.parametrize("name", FUSABLE)
def test_fused_lr_wd_mult_asymmetry(name, monkeypatch):
    """Per-param lr_mult/wd_mult become stacked scalar operands — the
    group stays fused and each param still sees ITS multiplier."""
    def build():
        o = _mk(name)
        o.set_lr_mult({0: 0.5, 2: 2.0})
        o.set_wd_mult({1: 0.0, 2: 3.0})
        return o
    wnp, gnp = _mk_tensors(seed=1)
    reset_apply_counters()
    ws_f, _ = _run_steps(build(), wnp, gnp)
    assert apply_counters["fused_calls"] == 3  # one per step, not per param
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    ws_l, _ = _run_steps(build(), wnp, gnp)
    _assert_close(ws_f, ws_l, name)


@pytest.mark.parametrize("name", ["sgd", "adam", "lamb"])
def test_fused_clip_gradient(name, monkeypatch):
    wnp, gnp = _mk_tensors(seed=2)
    ws_f, _ = _run_steps(_mk(name, clip_gradient=0.1), wnp, gnp)
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    ws_l, _ = _run_steps(_mk(name, clip_gradient=0.1), wnp, gnp)
    _assert_close(ws_f, ws_l, name)


@pytest.mark.parametrize("name", ["sgd", "adam"])
def test_fused_multi_precision_master(name, monkeypatch):
    """bf16 weights + fp32 master: fused keeps the weight bf16, carries
    the f32 master/state, and matches the legacy mp loop."""
    wnp, gnp = _mk_tensors(dtype=onp.float32, seed=3)
    wnp = [w.astype(jnp.bfloat16) for w in wnp]
    gnp = [g.astype(jnp.bfloat16) for g in gnp]
    ws_f, ss_f = _run_steps(_mk(name, multi_precision=True), wnp, gnp,
                            mp=True)
    for w, s in zip(ws_f, ss_f):
        assert w._data.dtype == jnp.bfloat16
        assert isinstance(s, tuple) and s[0].dtype == jnp.float32
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    ws_l, ss_l = _run_steps(_mk(name, multi_precision=True), wnp, gnp,
                            mp=True)
    # master copies advance in f32 on both paths — tight tolerance there
    for i, (sf, sl) in enumerate(zip(ss_f, ss_l)):
        onp.testing.assert_allclose(
            onp.asarray(sf[0]), onp.asarray(sl[0]), rtol=2e-5, atol=1e-6,
            err_msg=f"{name} master {i}")
    _assert_close(ws_f, ws_l, name, rtol=1e-2, atol=1e-2)  # bf16 rounding


def test_fused_bf16_non_mp_close(monkeypatch):
    """Raw bf16 (no master): fused promotes lr math to f32 — documented
    ulp-close, not bit-identical."""
    wnp, gnp = _mk_tensors(seed=4)
    wnp = [w.astype(jnp.bfloat16) for w in wnp]
    gnp = [g.astype(jnp.bfloat16) for g in gnp]
    ws_f, _ = _run_steps(_mk("sgd"), wnp, gnp)
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    ws_l, _ = _run_steps(_mk("sgd"), wnp, gnp)
    _assert_close(ws_f, ws_l, "sgd-bf16", rtol=2e-2, atol=2e-2)


def test_sparse_grad_falls_back_dense_stays_fused(monkeypatch):
    """A row_sparse grad takes the legacy per-param path; the dense
    params of the same call still fuse into one jitted apply."""
    wnp, gnp = _mk_tensors(seed=5, shapes=[(4, 5), (4, 5), (4, 5)])
    rs_np = onp.zeros((4, 5), onp.float32)
    rs_np[1] = gnp[1][1]
    rs = sp.RowSparseNDArray(rs_np[1:2].copy(), onp.array([1]), (4, 5))
    grads = [NDArray(jnp.array(gnp[0])), rs, NDArray(jnp.array(gnp[2]))]
    reset_apply_counters()
    ws_f, _ = _run_steps(_mk("sgd"), wnp, gnp, steps=1, grads=grads)
    assert apply_counters["fallback_params"] == 1
    assert apply_counters["fused_calls"] == 1
    assert apply_counters["fused_params"] == 2
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    ws_l, _ = _run_steps(_mk("sgd"), wnp, gnp, steps=1, grads=[
        NDArray(jnp.array(gnp[0])),
        sp.RowSparseNDArray(rs_np[1:2].copy(), onp.array([1]), (4, 5)),
        NDArray(jnp.array(gnp[2]))])
    _assert_close(ws_f, ws_l, "sgd-sparse-fallback")


def test_sgld_not_fused():
    """SGLD's host-RNG rule opts out of fusion entirely."""
    wnp, gnp = _mk_tensors(seed=6)
    mx.random.seed(0)
    reset_apply_counters()
    _run_steps(_mk("sgld"), wnp, gnp, steps=1)
    assert apply_counters["fused_calls"] == 0
    assert apply_counters["fallback_params"] == len(wnp)


def _many_param_trainer(n, optimizer="sgd", opt_params=None, dtypes=None):
    rng = onp.random.RandomState(7)
    params = []
    for i in range(n):
        dt = dtypes[i % len(dtypes)] if dtypes else "float32"
        p = Parameter(f"w{i}", shape=(3, 4), dtype=dt)
        p.initialize(init=mx.init.Uniform())
        p.grad()._rebind(jnp.asarray(rng.randn(3, 4), p.data()._data.dtype))
        params.append(p)
    trainer = gluon.Trainer(
        params, optimizer, opt_params or {"learning_rate": 0.01},
        kvstore=None)
    return params, trainer


def test_dispatch_count_one_call_per_group_not_per_param():
    """Acceptance: a >=50-param Trainer.step issues O(#groups) jitted
    optimizer-apply calls (here: 1 group), not O(#params)."""
    params, trainer = _many_param_trainer(60)
    reset_apply_counters()
    trainer.step(1)
    assert apply_counters["fused_calls"] == 1
    assert apply_counters["fused_params"] == 60
    assert apply_counters["fallback_params"] == 0
    # steady state: still one dispatch per step
    trainer.step(1)
    assert apply_counters["fused_calls"] == 2


def test_dispatch_count_groups_by_dtype():
    params, trainer = _many_param_trainer(
        50, dtypes=["float32", "bfloat16"])
    reset_apply_counters()
    trainer.step(1)
    assert apply_counters["fused_calls"] == 2  # one per dtype group
    assert apply_counters["fused_params"] == 50


def test_env_escape_hatch_disables_fusion(monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    params, trainer = _many_param_trainer(50)
    reset_apply_counters()
    trainer.step(1)
    assert apply_counters["fused_calls"] == 0
    assert apply_counters["fallback_params"] == 50


def test_trainer_fused_step_value():
    """End-to-end: fused Trainer.step produces the analytically expected
    SGD update (same assertion style as test_gluon.test_trainer_sgd_step,
    but through the fused path with many params)."""
    params, trainer = _many_param_trainer(
        8, opt_params={"learning_rate": 0.1})
    before = [onp.asarray(p.data()._data).copy() for p in params]
    grads = [onp.asarray(p.grad()._data).copy() for p in params]
    trainer.step(1)
    for p, b, g in zip(params, before, grads):
        onp.testing.assert_allclose(
            onp.asarray(p.data()._data), b - 0.1 * g, rtol=1e-6, atol=1e-7)


def test_kvstore_server_push_is_fused(monkeypatch):
    """update_on_kvstore: a list push applies the server-side optimizer
    as ONE fused multi_update over the whole wave."""
    from mxnet_tpu import kvstore as kv_mod
    rng = onp.random.RandomState(8)
    wnp = [rng.randn(3, 4).astype(onp.float32) for _ in range(6)]
    gnp = [rng.randn(3, 4).astype(onp.float32) for _ in range(6)]

    def run():
        kv = kv_mod.create("local")
        kv.set_optimizer(opt_mod.create("sgd", learning_rate=0.1,
                                        momentum=0.9))
        for i, w in enumerate(wnp):
            kv.init(i, NDArray(jnp.array(w)))
        for _ in range(2):
            kv.push(list(range(6)),
                    [NDArray(jnp.array(g)) for g in gnp])
        outs = [NDArray(jnp.zeros((3, 4), jnp.float32)) for _ in range(6)]
        kv.pull(list(range(6)), outs)
        return outs

    reset_apply_counters()
    fused = run()
    assert apply_counters["fused_calls"] == 2  # one per push wave
    assert apply_counters["fused_params"] == 12
    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "0")
    legacy = run()
    _assert_close(fused, legacy, "kvstore-server")


def test_optimizer_pickles_without_executable_cache(tmp_path):
    """The jitted executable cache must not leak into checkpoints
    (kvstore save_optimizer_states pickles the optimizer)."""
    import pickle
    opt = _mk("adam")
    wnp, gnp = _mk_tensors(seed=9)
    _run_steps(opt, wnp, gnp, steps=1)
    assert opt.__dict__.get("_fused_cache")
    blob = pickle.dumps(opt)
    opt2 = pickle.loads(blob)
    assert "_fused_cache" not in opt2.__dict__
    # and the restored optimizer still updates (rebuilds its cache)
    _run_steps(opt2, wnp, gnp, steps=1)


def test_hyperparam_mutation_retraces():
    """Mutating a closed-over hyperparameter (momentum) must not replay
    the stale executable."""
    opt = _mk("sgd")
    wnp, gnp = _mk_tensors(seed=10, shapes=[(4, 5)])
    ws, ss = _run_steps(opt, wnp, gnp, steps=1)
    opt.momentum = 0.0  # rule branches on it at trace time
    w2 = [NDArray(jnp.array(wnp[0]))]
    g2 = [NDArray(jnp.array(gnp[0]))]
    s2 = [None]  # momentum-0 SGD state
    opt.multi_update([0], w2, g2, s2)
    expected = wnp[0] - 0.05 * (0.5 * gnp[0] + 0.01 * wnp[0])
    onp.testing.assert_allclose(onp.asarray(w2[0]._data), expected,
                                rtol=2e-5, atol=1e-6)
