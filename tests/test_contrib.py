"""gluon.contrib tests (reference tests/python/unittest/test_gluon_contrib.py
coverage; SURVEY.md §3.2 "Gluon contrib")."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.gluon.contrib.estimator import (Estimator, CheckpointHandler,
                                               EarlyStoppingHandler,
                                               StoppingHandler)
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader


class TestContribNN:
    def test_pixel_shuffle_2d_matches_torch(self):
        import torch
        ps = cnn.PixelShuffle2D(2)
        x = mx.nd.array(onp.arange(72).reshape(1, 8, 3, 3)
                        .astype(onp.float32))
        ref = torch.pixel_shuffle(torch.tensor(x.asnumpy()), 2).numpy()
        onp.testing.assert_allclose(ps(x).asnumpy(), ref)

    def test_pixel_shuffle_1d_3d_shapes(self):
        assert cnn.PixelShuffle1D(3)(mx.nd.ones((2, 6, 5))).shape == (2, 2, 15)
        assert cnn.PixelShuffle3D((2, 2, 2))(
            mx.nd.ones((1, 8, 2, 3, 4))).shape == (1, 1, 4, 6, 8)

    def test_concurrent_and_identity(self):
        hc = cnn.HybridConcurrent(axis=1)
        hc.add(cnn.Identity())
        hc.add(cnn.Identity())
        assert hc(mx.nd.ones((2, 3))).shape == (2, 6)

    def test_sparse_embedding_forward(self):
        emb = cnn.SparseEmbedding(10, 4)
        emb.initialize(mx.init.Xavier())
        out = emb(mx.nd.array(onp.array([1, 3], onp.float32)))
        assert out.shape == (2, 4)


class TestConvRNN:
    def test_conv2d_lstm_unroll(self):
        cell = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=5,
                                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize(mx.init.Xavier())
        seq = mx.nd.array(onp.random.rand(2, 4, 3, 8, 8).astype(onp.float32))
        outputs, states = cell.unroll(4, seq, layout="NTC")
        assert outputs.shape == (2, 4, 5, 8, 8)
        assert states[0].shape == (2, 5, 8, 8)
        assert states[1].shape == (2, 5, 8, 8)

    def test_conv1d_gru_unroll(self):
        cell = crnn.Conv1DGRUCell(input_shape=(2, 6), hidden_channels=4,
                                  i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize(mx.init.Xavier())
        o, s = cell.unroll(3, mx.nd.ones((2, 3, 2, 6)), layout="NTC")
        assert o.shape == (2, 3, 4, 6)

    def test_even_h2h_kernel_rejected(self):
        from mxnet_tpu.base import MXNetError
        with pytest.raises(MXNetError):
            crnn.Conv2DRNNCell(input_shape=(3, 8, 8), hidden_channels=4,
                               i2h_kernel=3, h2h_kernel=2)

    def test_variational_dropout_cell(self):
        from mxnet_tpu import autograd
        from mxnet_tpu.gluon.rnn import LSTMCell
        base = LSTMCell(8)
        cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
        cell.initialize(mx.init.Xavier())
        x = mx.nd.ones((2, 5, 4))
        with autograd.record():
            out, _ = cell.unroll(5, x, layout="NTC")
        assert out.shape == (2, 5, 8)


class TestEstimator:
    def _data(self):
        rng = onp.random.RandomState(0)
        X = rng.rand(80, 10).astype(onp.float32)
        Y = (X.sum(1) > 5).astype(onp.float32)
        return DataLoader(ArrayDataset(X, Y), batch_size=16)

    def test_fit_and_evaluate(self):
        dl = self._data()
        net = gluon.nn.Dense(2)
        net.initialize(mx.init.Xavier(), ctx=mx.cpu())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.5})
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=[mx.metric.Accuracy()], trainer=trainer,
                        context=mx.cpu())
        est.fit(dl, epochs=8)
        res = dict(est.evaluate(dl))
        assert res["accuracy"] > 0.7

    def test_checkpoint_handler(self, tmp_path):
        dl = self._data()
        net = gluon.nn.Dense(2)
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=[mx.metric.Accuracy()], context=mx.cpu())
        ck = str(tmp_path / "ckpts")
        est.fit(dl, epochs=2,
                event_handlers=[CheckpointHandler(ck, save_best=True,
                                                  monitor=est.train_metrics[0])])
        files = os.listdir(ck)
        assert any("epoch" in f for f in files)
        assert any("best" in f for f in files)

    def test_stopping_by_batches(self):
        dl = self._data()
        net = gluon.nn.Dense(2)
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=[mx.metric.Accuracy()], context=mx.cpu())
        est.fit(dl, batches=3)

    def test_early_stopping(self):
        dl = self._data()
        net = gluon.nn.Dense(2)
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=[mx.metric.Accuracy()], context=mx.cpu())
        es = EarlyStoppingHandler(monitor=est.train_metrics[0], patience=1)
        est.fit(dl, epochs=20, event_handlers=[es])
        # with patience 1 on a tiny problem, must stop well before 20
        assert es.current_epoch < 20
