"""Test config: force an 8-device virtual CPU platform so collective /
sharding tests run without TPU hardware (mirrors the reference's
multi-process-on-localhost nightly pattern, SURVEY.md §7 test strategy).
Must set XLA flags before jax initializes."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as onp
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx
    onp.random.seed(0)
    mx.random.seed(0)
    yield
