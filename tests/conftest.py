"""Test config: force an 8-device virtual CPU platform so collective /
sharding tests run without TPU hardware (mirrors the reference's
multi-process-on-localhost nightly pattern, SURVEY.md §7 test strategy).
Must set XLA flags before jax initializes."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The sandbox injects a TPU-tunnel PJRT plugin ("axon") via sitecustomize,
# which runs before this conftest and imports jax with JAX_PLATFORMS=axon in
# the env; first axon-backend initialization dials the tunnel (can hang for
# minutes).  Overriding the config snapshot (not just the env var) makes
# backends() initialize only cpu, so the tunnel is never dialed.  The axon
# factory stays registered — harmless, and removing it would unregister the
# "tpu" platform name that Pallas interpret-mode lowering relies on.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as onp
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx
    onp.random.seed(0)
    mx.random.seed(0)
    yield
