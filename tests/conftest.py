"""Test config: force an 8-device virtual CPU platform so collective /
sharding tests run without TPU hardware (mirrors the reference's
multi-process-on-localhost nightly pattern, SURVEY.md §7 test strategy).
Must set XLA flags before jax initializes."""
import os
import sys

# make `import mxnet_tpu` work no matter where pytest is invoked from
# (pytest.ini pins rootdir, this pins the import path)
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The sandbox injects a TPU-tunnel PJRT plugin ("axon") via sitecustomize,
# which runs before this conftest and imports jax with JAX_PLATFORMS=axon in
# the env; first axon-backend initialization dials the tunnel (can hang for
# minutes).  Overriding the config snapshot (not just the env var) makes
# backends() initialize only cpu, so the tunnel is never dialed.  The axon
# factory stays registered — harmless, and removing it would unregister the
# "tpu" platform name that Pallas interpret-mode lowering relies on.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as onp
import pytest


def _load_slow_ids():
    path = os.path.join(os.path.dirname(__file__), "slow_tests.txt")
    try:
        with open(path) as fh:
            return {ln.strip() for ln in fh
                    if ln.strip() and not ln.startswith("#")}
    except OSError:
        return set()


def pytest_collection_modifyitems(config, items):
    """Apply the measured fast/slow split (VERDICT r4 item 9): every
    collected test gets exactly one of the two markers; membership comes
    from tests/slow_tests.txt (regenerate with tools/gen_slow_marks.py
    after perf-relevant suite changes).  Unlisted tests default to fast —
    new tests enter the gate until a regeneration measures them."""
    slow_ids = _load_slow_ids()
    seen = set()
    # the op-conformance sweep is ~1900 nodes; the gate keeps a 1/8
    # rotation (structural, so newly registered ops join automatically)
    # while measured-slow nodes stay out of the gate regardless
    conf_idx = 0
    for item in items:
        seen.add(item.nodeid)
        slow = item.nodeid in slow_ids
        if "test_op_conformance" in item.nodeid and \
                "::test_" in item.nodeid and "[" in item.nodeid:
            slow = slow or (conf_idx % 8 != 0)
            conf_idx += 1
        if slow:
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.fast)
    # staleness guard: ids that no longer collect mean the list rotted
    # (only meaningful when the whole suite was collected — single-file
    # runs legitimately miss most listed ids)
    n_files = len({i.nodeid.split("::")[0] for i in items})
    if n_files >= 30:
        dead = slow_ids - seen
        if dead:
            import warnings
            warnings.warn(
                f"tests/slow_tests.txt lists {len(dead)} node ids that no "
                f"longer exist (e.g. {sorted(dead)[:3]}); regenerate with "
                "tools/gen_slow_marks.py")


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx
    onp.random.seed(0)
    mx.random.seed(0)
    yield
