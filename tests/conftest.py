"""Test config: force an 8-device virtual CPU platform so collective /
sharding tests run without TPU hardware (mirrors the reference's
multi-process-on-localhost nightly pattern, SURVEY.md §7 test strategy).
Must set XLA flags before jax initializes."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The sandbox injects a TPU-tunnel PJRT plugin ("axon") via sitecustomize,
# which runs before this conftest and registers backend factories whose
# first initialization dials the tunnel (can hang for minutes).  Tests run
# on the virtual CPU mesh, so drop every non-cpu factory before any jax
# backend is initialized.
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

for _plat in [p for p in _xb._backend_factories if p != "cpu"]:
    _xb._backend_factories.pop(_plat, None)
# sitecustomize imported jax with JAX_PLATFORMS=axon already in the env, so
# the config snapshot must be overridden as well as the env var.
jax.config.update("jax_platforms", "cpu")

import numpy as onp
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx
    onp.random.seed(0)
    mx.random.seed(0)
    yield
