"""Flat C ABI (native/mxtpu_c_api.cc — the reference's c_predict_api
surface, SURVEY.md §3.1 "C API" row).

Two hosts are exercised:
- a ctypes caller (C ABI from an existing Python process: the embedded
  interpreter is reused);
- a REAL standalone C program, compiled with g++ at test time and run in
  a subprocess — the multi-language-bindings story (SURVEY.md §1
  capability 6): any FFI host can link libmxtpu_capi.so.
"""
import ctypes
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_capi.so")


def _build_lib():
    # unconditional: make is incremental, and a stale .so must never
    # green-light old binaries
    subprocess.run(["make", "capi"], cwd=os.path.join(REPO, "native"),
                   check=True, capture_output=True)
    return LIB


def _export_model(tmp_path):
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu", in_units=4))
    net.add(gluon.nn.Dense(3, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(onp.random.RandomState(0).rand(2, 4)
                    .astype("float32"))
    net(x)  # trace
    prefix = str(tmp_path / "model")
    net.export(prefix)
    return prefix + "-symbol.json", prefix + "-0000.params", x


class TestCtypesHost:
    def test_predict_round_trip(self, tmp_path):
        _build_lib()
        sym, params, x = _export_model(tmp_path)
        ref = None
        from mxnet_tpu.predictor import Predictor
        pred = Predictor(sym, params, {"data": (2, 4)})
        pred.set_input("data", x.asnumpy())
        pred.run()
        ref = pred.get_output(0).asnumpy()

        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        ver = ctypes.c_int()
        assert lib.MXGetVersion(ctypes.byref(ver)) == 0
        assert ver.value == 10900

        handle = ctypes.c_void_p()
        keys = (ctypes.c_char_p * 1)(b"data")
        indptr = (ctypes.c_uint * 2)(0, 2)
        shape = (ctypes.c_uint * 2)(2, 4)
        rc = lib.MXPredCreate(sym.encode(), params.encode(), 1, 0, 1,
                              keys, indptr, shape, ctypes.byref(handle))
        assert rc == 0, lib.MXGetLastError()

        data = x.asnumpy().reshape(-1)
        buf = (ctypes.c_float * data.size)(*data.tolist())
        assert lib.MXPredSetInput(handle, b"data", buf, data.size) == 0, \
            lib.MXGetLastError()
        assert lib.MXPredForward(handle) == 0, lib.MXGetLastError()

        n_out = ctypes.c_uint()
        assert lib.MXPredGetNumOutputs(handle, ctypes.byref(n_out)) == 0
        assert n_out.value == 1

        sh_data = ctypes.POINTER(ctypes.c_uint)()
        sh_ndim = ctypes.c_uint()
        assert lib.MXPredGetOutputShape(handle, 0, ctypes.byref(sh_data),
                                        ctypes.byref(sh_ndim)) == 0
        shape_out = tuple(sh_data[i] for i in range(sh_ndim.value))
        assert shape_out == (2, 3)

        n = 6
        out = (ctypes.c_float * n)()
        assert lib.MXPredGetOutput(handle, 0, out, n) == 0, \
            lib.MXGetLastError()
        got = onp.asarray(list(out), onp.float32).reshape(2, 3)
        onp.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        assert lib.MXPredFree(handle) == 0

    def test_error_surface(self, tmp_path):
        _build_lib()
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        handle = ctypes.c_void_p()
        keys = (ctypes.c_char_p * 1)(b"data")
        indptr = (ctypes.c_uint * 2)(0, 1)
        shape = (ctypes.c_uint * 1)(4)
        rc = lib.MXPredCreate(b"/nonexistent-symbol.json", b"", 1, 0, 1,
                              keys, indptr, shape, ctypes.byref(handle))
        assert rc == -1
        assert len(lib.MXGetLastError()) > 0


C_HOST = r"""
#include <stdio.h>
#include <stdlib.h>
typedef unsigned int mx_uint;
typedef void* PredictorHandle;
#ifdef __cplusplus
extern "C" {
#endif
extern const char* MXGetLastError();
extern int MXGetVersion(int*);
extern int MXPredCreate(const char*, const char*, int, int, mx_uint,
                        const char**, const mx_uint*, const mx_uint*,
                        PredictorHandle*);
extern int MXPredSetInput(PredictorHandle, const char*, const float*,
                          mx_uint);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutputShape(PredictorHandle, mx_uint, mx_uint**,
                                mx_uint*);
extern int MXPredGetOutput(PredictorHandle, mx_uint, float*, mx_uint);
extern int MXPredFree(PredictorHandle);
#ifdef __cplusplus
}
#endif

#define CHECK(x) if ((x) != 0) { \
    fprintf(stderr, "FAIL: %s\n", MXGetLastError()); return 1; }

int main(int argc, char** argv) {
  int ver; CHECK(MXGetVersion(&ver));
  printf("version=%d\n", ver);
  const char* keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {2, 4};
  PredictorHandle h;
  CHECK(MXPredCreate(argv[1], argv[2], 1, 0, 1, keys, indptr, shape, &h));
  float in[8];
  for (int i = 0; i < 8; ++i) in[i] = 0.125f * i;
  CHECK(MXPredSetInput(h, "data", in, 8));
  CHECK(MXPredForward(h));
  mx_uint *sh, ndim;
  CHECK(MXPredGetOutputShape(h, 0, &sh, &ndim));
  printf("ndim=%u shape=%u,%u\n", ndim, sh[0], sh[1]);
  float out[6];
  CHECK(MXPredGetOutput(h, 0, out, 6));
  printf("out=");
  for (int i = 0; i < 6; ++i) printf("%.6f ", out[i]);
  printf("\n");
  CHECK(MXPredFree(h));
  printf("C_HOST_OK\n");
  return 0;
}
"""


class TestStandaloneCHost:
    def test_compiled_c_program(self, tmp_path):
        """Compile a real C host with g++, link libmxtpu_capi.so, run it
        in a fresh process (its own embedded interpreter), and check the
        output matches the python-side predictor."""
        _build_lib()
        sym, params, _x = _export_model(tmp_path)
        src = tmp_path / "host.c"
        src.write_text(C_HOST)
        exe = tmp_path / "host"
        libdir = os.path.dirname(LIB)
        subprocess.run(
            ["g++", str(src), "-o", str(exe), f"-L{libdir}",
             "-lmxtpu_capi", f"-Wl,-rpath,{libdir}"],
            check=True, capture_output=True, text=True)

        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run([str(exe), sym, params],
                              capture_output=True, text=True, env=env,
                              timeout=300)
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-1500:])
        assert "C_HOST_OK" in proc.stdout
        assert "version=10900" in proc.stdout
        assert "ndim=2 shape=2,3" in proc.stdout

        # cross-check values against the python predictor
        from mxnet_tpu.predictor import Predictor
        pred = Predictor(sym, params, {"data": (2, 4)})
        x = (onp.arange(8, dtype=onp.float32) * 0.125).reshape(2, 4)
        pred.set_input("data", x)
        pred.run()
        ref = pred.get_output(0).asnumpy().reshape(-1)
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("out=")][0]
        got = onp.asarray([float(v) for v in line[4:].split()],
                          onp.float32)
        onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------- #
# Training ABI (VERDICT r3 item 5): a REAL C host trains an MNIST-style
# MLP through MXNDArray* / MXSymbol* / MXExecutor* — create arrays, infer
# shapes from data shapes alone, bind, forward, backward, SGD in C.
# --------------------------------------------------------------------- #

C_TRAIN_HOST = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
typedef unsigned int mx_uint;
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
#ifdef __cplusplus
extern "C" {
#endif
extern const char* MXGetLastError();
extern int MXNDArrayCreate(const mx_uint*, mx_uint, int, int, int,
                           NDArrayHandle*);
extern int MXNDArrayFree(NDArrayHandle);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle, const void*,
                                    unsigned long);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle, void*, unsigned long);
extern int MXNDArrayGetShape(NDArrayHandle, mx_uint*, const mx_uint**);
extern int MXSymbolCreateFromFile(const char*, SymbolHandle*);
extern int MXSymbolFree(SymbolHandle);
extern int MXSymbolListArguments(SymbolHandle, mx_uint*, const char***);
extern int MXSymbolInferShape(SymbolHandle, mx_uint, const char**,
    const mx_uint*, const mx_uint*,
    mx_uint*, const mx_uint**, const mx_uint***,
    mx_uint*, const mx_uint**, const mx_uint***,
    mx_uint*, const mx_uint**, const mx_uint***, int*);
extern int MXExecutorBind(SymbolHandle, int, int, mx_uint, NDArrayHandle*,
                          NDArrayHandle*, mx_uint*, mx_uint,
                          NDArrayHandle*, ExecutorHandle*);
extern int MXExecutorForward(ExecutorHandle, int);
extern int MXExecutorBackward(ExecutorHandle, mx_uint, NDArrayHandle*);
extern int MXExecutorOutputs(ExecutorHandle, mx_uint*, NDArrayHandle**);
extern int MXExecutorFree(ExecutorHandle);
#ifdef __cplusplus
}
#endif

#define CHECK(x) if ((x) != 0) { \
    fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, \
            MXGetLastError()); return 1; }

#define B 64
#define NF 16
#define NC 3

static unsigned lcg_state = 12345u;
static float frand(void) {  /* deterministic U(-0.5, 0.5) */
  lcg_state = lcg_state * 1664525u + 1013904223u;
  return ((lcg_state >> 8) & 0xFFFFFF) / 16777216.0f - 0.5f;
}

int main(int argc, char** argv) {
  if (argc < 2) { fprintf(stderr, "usage: host symbol.json\n"); return 2; }
  SymbolHandle sym;
  CHECK(MXSymbolCreateFromFile(argv[1], &sym));

  mx_uint n_args; const char** arg_names;
  CHECK(MXSymbolListArguments(sym, &n_args, &arg_names));
  printf("n_args=%u\n", n_args);

  /* infer every argument shape from data+label alone */
  const char* keys[] = {"data", "label"};
  mx_uint indptr[] = {0, 2, 3};
  mx_uint shape_data[] = {B, NF, B};
  mx_uint in_n, out_n, aux_n; int complete;
  const mx_uint *in_nd, *out_nd, *aux_nd;
  const mx_uint **in_sh, **out_sh, **aux_sh;
  CHECK(MXSymbolInferShape(sym, 2, keys, indptr, shape_data,
                           &in_n, &in_nd, &in_sh,
                           &out_n, &out_nd, &out_sh,
                           &aux_n, &aux_nd, &aux_sh, &complete));
  printf("inferred in=%u out=%u complete=%d\n", in_n, out_n, complete);
  if (in_n != n_args) { fprintf(stderr, "arg count mismatch\n"); return 1; }

  /* create arg + grad arrays from the inferred shapes */
  NDArrayHandle args[16], grads[16];
  mx_uint reqs[16];
  mx_uint sizes[16];
  for (mx_uint i = 0; i < in_n; ++i) {
    CHECK(MXNDArrayCreate(in_sh[i], in_nd[i], 1, 0, 0, &args[i]));
    mx_uint sz = 1;
    for (mx_uint d = 0; d < in_nd[i]; ++d) sz *= in_sh[i][d];
    sizes[i] = sz;
    int is_param = strcmp(arg_names[i], "data") != 0 &&
                   strcmp(arg_names[i], "label") != 0;
    reqs[i] = is_param ? 1 : 0;  /* kWriteTo : kNullOp */
    if (is_param) {
      CHECK(MXNDArrayCreate(in_sh[i], in_nd[i], 1, 0, 0, &grads[i]));
      float* init = (float*)malloc(sz * sizeof(float));
      for (mx_uint j = 0; j < sz; ++j) init[j] = 0.2f * frand();
      CHECK(MXNDArraySyncCopyFromCPU(args[i], init, sz));
      free(init);
    } else {
      grads[i] = NULL;
    }
  }

  /* synthetic separable data: 3 clusters on the first 3 features */
  float x[B * NF], y[B];
  for (int i = 0; i < B; ++i) {
    int c = i % NC;
    y[i] = (float)c;
    for (int f = 0; f < NF; ++f)
      x[i * NF + f] = 0.3f * frand() + (f == c ? 2.0f : 0.0f);
  }
  for (mx_uint i = 0; i < in_n; ++i) {
    if (strcmp(arg_names[i], "data") == 0)
      CHECK(MXNDArraySyncCopyFromCPU(args[i], x, B * NF));
    if (strcmp(arg_names[i], "label") == 0)
      CHECK(MXNDArraySyncCopyFromCPU(args[i], y, B));
  }

  ExecutorHandle exec;
  CHECK(MXExecutorBind(sym, 1, 0, in_n, args, grads, reqs, 0, NULL,
                       &exec));

  float first_loss = 0.0f, loss = 0.0f;
  float lr = 0.5f;
  for (int step = 0; step < 40; ++step) {
    CHECK(MXExecutorForward(exec, 1));
    mx_uint n_out; NDArrayHandle* outs;
    CHECK(MXExecutorOutputs(exec, &n_out, &outs));
    CHECK(MXNDArraySyncCopyToCPU(outs[0], &loss, 1));
    for (mx_uint i = 0; i < n_out; ++i) MXNDArrayFree(outs[i]);
    if (step == 0) first_loss = loss;
    CHECK(MXExecutorBackward(exec, 0, NULL));
    /* SGD in C: read grad, update, write back */
    for (mx_uint i = 0; i < in_n; ++i) {
      if (reqs[i] == 0) continue;
      float* w = (float*)malloc(sizes[i] * sizeof(float));
      float* g = (float*)malloc(sizes[i] * sizeof(float));
      CHECK(MXNDArraySyncCopyToCPU(args[i], w, sizes[i]));
      CHECK(MXNDArraySyncCopyToCPU(grads[i], g, sizes[i]));
      for (mx_uint j = 0; j < sizes[i]; ++j) w[j] -= lr * g[j];
      CHECK(MXNDArraySyncCopyFromCPU(args[i], w, sizes[i]));
      free(w); free(g);
    }
  }
  printf("first_loss=%.6f last_loss=%.6f\n", first_loss, loss);

  CHECK(MXExecutorFree(exec));
  for (mx_uint i = 0; i < in_n; ++i) {
    CHECK(MXNDArrayFree(args[i]));
    if (grads[i]) CHECK(MXNDArrayFree(grads[i]));
  }
  CHECK(MXSymbolFree(sym));
  if (!(loss < 0.5f * first_loss)) {
    fprintf(stderr, "loss did not decrease enough\n");
    return 1;
  }
  printf("C_TRAIN_OK\n");
  return 0;
}
"""


class TestCTrainingABI:
    def _export_train_symbol(self, tmp_path):
        B, F, H, C = 64, 16, 32, 3
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("label")
        w1 = mx.sym.Variable("w1")
        b1 = mx.sym.Variable("b1")
        w2 = mx.sym.Variable("w2")
        b2 = mx.sym.Variable("b2")
        h = mx.sym.Activation(
            mx.sym.FullyConnected(data, w1, b1, num_hidden=H),
            act_type="relu")
        out = mx.sym.FullyConnected(h, w2, b2, num_hidden=C)
        loss = mx.sym.softmax_cross_entropy(out, label) / float(B)
        path = str(tmp_path / "train-symbol.json")
        loss.save(path)
        return path

    def test_c_host_trains_mlp(self, tmp_path):
        """Compile a standalone C program that creates NDArrays, infers
        shapes from the data shapes alone, binds an executor, and runs a
        40-step SGD loop entirely through the flat C ABI — the loss must
        drop below half its initial value."""
        _build_lib()
        symf = self._export_train_symbol(tmp_path)
        src = tmp_path / "train_host.c"
        src.write_text(C_TRAIN_HOST)
        exe = tmp_path / "train_host"
        libdir = os.path.dirname(LIB)
        subprocess.run(
            ["g++", str(src), "-o", str(exe), f"-L{libdir}",
             "-lmxtpu_capi", f"-Wl,-rpath,{libdir}"],
            check=True, capture_output=True, text=True)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run([str(exe), symf], capture_output=True,
                              text=True, env=env, timeout=600)
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
        assert "C_TRAIN_OK" in proc.stdout
        assert "n_args=6" in proc.stdout
        assert "inferred in=6 out=1 complete=1" in proc.stdout

    def test_training_abi_via_ctypes(self, tmp_path):
        """Same ABI from a ctypes host (reuses the in-process
        interpreter): NDArray round-trip + shape query."""
        _build_lib()
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        h = ctypes.c_void_p()
        shape = (ctypes.c_uint * 2)(3, 4)
        assert lib.MXNDArrayCreate(shape, 2, 1, 0, 0,
                                   ctypes.byref(h)) == 0, \
            lib.MXGetLastError()
        vals = onp.arange(12, dtype=onp.float32)
        buf = (ctypes.c_float * 12)(*vals.tolist())
        assert lib.MXNDArraySyncCopyFromCPU(h, buf, 12) == 0, \
            lib.MXGetLastError()
        out = (ctypes.c_float * 12)()
        assert lib.MXNDArraySyncCopyToCPU(h, out, 12) == 0, \
            lib.MXGetLastError()
        onp.testing.assert_allclose(onp.asarray(out), vals)
        ndim = ctypes.c_uint()
        pdata = ctypes.POINTER(ctypes.c_uint)()
        assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                     ctypes.byref(pdata)) == 0
        assert ndim.value == 2 and pdata[0] == 3 and pdata[1] == 4
        # size mismatch must fail in BOTH directions with a clear error
        small = (ctypes.c_float * 2)()
        assert lib.MXNDArraySyncCopyToCPU(h, small, 2) == -1
        assert b"size mismatch" in lib.MXGetLastError()
        big = (ctypes.c_float * 100)()
        assert lib.MXNDArraySyncCopyToCPU(h, big, 100) == -1
        assert b"size mismatch" in lib.MXGetLastError()
        assert lib.MXNDArrayFree(h) == 0

    def test_ndarray_save_load_dtype_via_ctypes(self, tmp_path):
        """MXNDArraySave/Load round-trip the shared .params bit-format
        from C-held handles; MXNDArrayGetDType returns the reference
        dtype enum; MXSymbolSaveToFile writes loadable json."""
        _build_lib()
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        h = ctypes.c_void_p()
        shape = (ctypes.c_uint * 2)(2, 3)
        assert lib.MXNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h)) == 0
        vals = onp.arange(6, dtype=onp.float32) * 0.5
        buf = (ctypes.c_float * 6)(*vals.tolist())
        assert lib.MXNDArraySyncCopyFromCPU(h, buf, 6) == 0
        dt = ctypes.c_int()
        assert lib.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0
        assert dt.value == 0  # kFloat32
        fn = str(tmp_path / "w.params").encode()
        keys = (ctypes.c_char_p * 1)(b"weight")
        assert lib.MXNDArraySave(fn, 1, ctypes.byref(h), keys) == 0, \
            lib.MXGetLastError()
        # python side reads the same file (shared bit-format)
        loaded = mx.nd.load(fn.decode())
        onp.testing.assert_allclose(loaded["weight"].asnumpy(),
                                    vals.reshape(2, 3))
        # C side loads it back
        n = ctypes.c_uint()
        arrs = ctypes.POINTER(ctypes.c_void_p)()
        nn = ctypes.c_uint()
        names = ctypes.POINTER(ctypes.c_char_p)()
        assert lib.MXNDArrayLoad(fn, ctypes.byref(n), ctypes.byref(arrs),
                                 ctypes.byref(nn),
                                 ctypes.byref(names)) == 0, \
            lib.MXGetLastError()
        assert n.value == 1 and nn.value == 1
        assert names[0] == b"weight"
        out = (ctypes.c_float * 6)()
        assert lib.MXNDArraySyncCopyToCPU(
            ctypes.c_void_p(arrs[0]), out, 6) == 0
        onp.testing.assert_allclose(onp.asarray(out), vals)
        lib.MXNDArrayFree(ctypes.c_void_p(arrs[0]))
        lib.MXNDArrayFree(h)

    def test_symbol_save_roundtrip_via_ctypes(self, tmp_path):
        _build_lib()
        symf = self._export_train_symbol(tmp_path)
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        sh = ctypes.c_void_p()
        assert lib.MXSymbolCreateFromFile(symf.encode(),
                                          ctypes.byref(sh)) == 0
        out = str(tmp_path / "resaved.json").encode()
        assert lib.MXSymbolSaveToFile(sh, out) == 0, lib.MXGetLastError()
        sym2 = mx.sym.load(out.decode())
        assert len(sym2.list_arguments()) == 6
        lib.MXSymbolFree(sh)


C_INVOKE_HOST = r"""
#include <stddef.h>
#include <stdio.h>
#include <string.h>

typedef unsigned int mx_uint;
typedef void *NDArrayHandle;
typedef void *OpHandle;

extern "C" {
extern int MXListAllOpNames(mx_uint *out_size, const char ***out_array);
extern int NNGetOpHandle(const char *name, OpHandle *out);
extern int MXImperativeInvoke(OpHandle creator, int num_inputs,
                              NDArrayHandle *inputs, int *num_outputs,
                              NDArrayHandle **outputs, int num_params,
                              const char **param_keys,
                              const char **param_vals);
extern int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                           int dev_id, int delay_alloc, NDArrayHandle *out);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                    size_t size);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data, size_t size);
extern int MXNDArrayFree(NDArrayHandle h);
extern const char *MXGetLastError();
}

#define CHECK(x) if ((x) != 0) { \
    printf("FAIL %s: %s\n", #x, MXGetLastError()); return 1; }

int main() {
  mx_uint n_ops = 0;
  const char **names = NULL;
  CHECK(MXListAllOpNames(&n_ops, &names));
  int have_dot = 0;
  for (mx_uint i = 0; i < n_ops; ++i)
    if (strcmp(names[i], "dot") == 0) have_dot = 1;
  printf("n_ops=%u have_dot=%d\n", n_ops, have_dot);

  OpHandle op_dot, op_sgd;
  CHECK(NNGetOpHandle("dot", &op_dot));
  CHECK(NNGetOpHandle("sgd_update", &op_sgd));

  /* dot: (2x3) x (3x2), eager, auto-allocated output */
  mx_uint sa[2] = {2, 3}, sb[2] = {3, 2};
  NDArrayHandle a, b;
  CHECK(MXNDArrayCreate(sa, 2, 1, 0, 0, &a));
  CHECK(MXNDArrayCreate(sb, 2, 1, 0, 0, &b));
  float av[6] = {1, 2, 3, 4, 5, 6}, bv[6] = {1, 0, 0, 1, 1, 1};
  CHECK(MXNDArraySyncCopyFromCPU(a, av, 6));
  CHECK(MXNDArraySyncCopyFromCPU(b, bv, 6));
  NDArrayHandle ins[2] = {a, b};
  int n_out = 0;
  NDArrayHandle *outs = NULL;
  CHECK(MXImperativeInvoke(op_dot, 2, ins, &n_out, &outs, 0, NULL, NULL));
  float y[4] = {0};
  CHECK(MXNDArraySyncCopyToCPU(outs[0], y, 4));
  printf("dot=[%g,%g,%g,%g] n_out=%d\n", y[0], y[1], y[2], y[3], n_out);
  /* [[1,2,3],[4,5,6]] @ [[1,0],[0,1],[1,1]] = [[4,5],[10,11]] */
  if (!(y[0] == 4 && y[1] == 5 && y[2] == 10 && y[3] == 11)) {
    printf("FAIL dot values\n");
    return 1;
  }
  CHECK(MXNDArrayFree(outs[0]));

  /* sgd_update in place: out = weight handle */
  mx_uint sw[1] = {4};
  NDArrayHandle w, g;
  CHECK(MXNDArrayCreate(sw, 1, 1, 0, 0, &w));
  CHECK(MXNDArrayCreate(sw, 1, 1, 0, 0, &g));
  float wv[4] = {1, 1, 1, 1}, gv[4] = {1, 2, 3, 4};
  CHECK(MXNDArraySyncCopyFromCPU(w, wv, 4));
  CHECK(MXNDArraySyncCopyFromCPU(g, gv, 4));
  NDArrayHandle uin[2] = {w, g};
  const char *uk[2] = {"lr", "wd"};
  const char *uv[2] = {"0.5", "0.0"};
  NDArrayHandle uout_arr[1] = {w};
  NDArrayHandle *uout = uout_arr;
  int n_uout = 1;
  CHECK(MXImperativeInvoke(op_sgd, 2, uin, &n_uout, &uout, 2, uk, uv));
  float wy[4] = {0};
  CHECK(MXNDArraySyncCopyToCPU(w, wy, 4));
  printf("sgd=[%g,%g,%g,%g]\n", wy[0], wy[1], wy[2], wy[3]);
  if (!(wy[0] == 0.5f && wy[1] == 0.0f && wy[2] == -0.5f
        && wy[3] == -1.0f)) {
    printf("FAIL sgd values\n");
    return 1;
  }

  /* unknown op must fail at lookup with a message */
  OpHandle nope;
  if (NNGetOpHandle("definitely_not_an_op", &nope) == 0) {
    printf("FAIL unknown op accepted\n");
    return 1;
  }
  printf("unknown_op_err=%s\n", MXGetLastError());
  printf("C_INVOKE_OK\n");
  return 0;
}
"""


class TestImperativeInvoke:
    """MXImperativeInvoke — the per-op C fast path (VERDICT r4 item 6;
    SURVEY.md §3.1 C API row, call stack §4.1)."""

    def test_compiled_c_host_invokes_ops(self, tmp_path):
        """A standalone C program lists ops, resolves handles by name,
        runs dot eagerly (auto-allocated output) and sgd_update in place
        (caller-supplied out handle), and sees lookup errors."""
        _build_lib()
        src = tmp_path / "invoke_host.c"
        src.write_text(C_INVOKE_HOST)
        exe = tmp_path / "invoke_host"
        libdir = os.path.dirname(LIB)
        subprocess.run(
            ["g++", str(src), "-o", str(exe), f"-L{libdir}",
             "-lmxtpu_capi", f"-Wl,-rpath,{libdir}"],
            check=True, capture_output=True, text=True)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run([str(exe)], capture_output=True, text=True,
                              env=env, timeout=600)
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
        assert "C_INVOKE_OK" in proc.stdout
        assert "have_dot=1" in proc.stdout
        assert "definitely_not_an_op" in proc.stdout

    def test_invoke_ex_stypes_and_attrs_via_ctypes(self):
        """MXImperativeInvokeEx reports dense stypes; string attrs parse
        python-literal style (tuples, floats); multi-output allocation
        returns one handle per output."""
        _build_lib()
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p

        def make_nd(arr):
            arr = onp.ascontiguousarray(arr, dtype=onp.float32)
            h = ctypes.c_void_p()
            shape = (ctypes.c_uint * arr.ndim)(*arr.shape)
            assert lib.MXNDArrayCreate(shape, arr.ndim, 1, 0, 0,
                                       ctypes.byref(h)) == 0
            buf = arr.ravel()
            cbuf = (ctypes.c_float * buf.size)(*buf.tolist())
            assert lib.MXNDArraySyncCopyFromCPU(h, cbuf, buf.size) == 0
            return h

        def read_nd(h, shape):
            out = (ctypes.c_float * int(onp.prod(shape)))()
            assert lib.MXNDArraySyncCopyToCPU(
                h, out, int(onp.prod(shape))) == 0, lib.MXGetLastError()
            return onp.asarray(out).reshape(shape)

        oh = ctypes.c_void_p()
        assert lib.NNGetOpHandle(b"transpose", ctypes.byref(oh)) == 0
        x = onp.arange(6, dtype=onp.float32).reshape(2, 3)
        hx = make_nd(x)
        n_out = ctypes.c_int(0)
        outs = ctypes.POINTER(ctypes.c_void_p)()
        stypes = ctypes.POINTER(ctypes.c_int)()
        keys = (ctypes.c_char_p * 1)(b"axes")
        vals = (ctypes.c_char_p * 1)(b"(1, 0)")
        assert lib.MXImperativeInvokeEx(
            oh, 1, ctypes.byref(ctypes.c_void_p(hx.value)),
            ctypes.byref(n_out), ctypes.byref(outs),
            1, keys, vals, ctypes.byref(stypes)) == 0, lib.MXGetLastError()
        assert n_out.value == 1 and stypes[0] == 0  # kDefaultStorage
        got = read_nd(ctypes.c_void_p(outs[0]), (3, 2))
        onp.testing.assert_allclose(got, x.T)
