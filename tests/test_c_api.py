"""Flat C ABI (native/mxtpu_c_api.cc — the reference's c_predict_api
surface, SURVEY.md §3.1 "C API" row).

Two hosts are exercised:
- a ctypes caller (C ABI from an existing Python process: the embedded
  interpreter is reused);
- a REAL standalone C program, compiled with g++ at test time and run in
  a subprocess — the multi-language-bindings story (SURVEY.md §1
  capability 6): any FFI host can link libmxtpu_capi.so.
"""
import ctypes
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_capi.so")


def _build_lib():
    # unconditional: make is incremental, and a stale .so must never
    # green-light old binaries
    subprocess.run(["make", "capi"], cwd=os.path.join(REPO, "native"),
                   check=True, capture_output=True)
    return LIB


def _export_model(tmp_path):
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu", in_units=4))
    net.add(gluon.nn.Dense(3, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(onp.random.RandomState(0).rand(2, 4)
                    .astype("float32"))
    net(x)  # trace
    prefix = str(tmp_path / "model")
    net.export(prefix)
    return prefix + "-symbol.json", prefix + "-0000.params", x


class TestCtypesHost:
    def test_predict_round_trip(self, tmp_path):
        _build_lib()
        sym, params, x = _export_model(tmp_path)
        ref = None
        from mxnet_tpu.predictor import Predictor
        pred = Predictor(sym, params, {"data": (2, 4)})
        pred.set_input("data", x.asnumpy())
        pred.run()
        ref = pred.get_output(0).asnumpy()

        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        ver = ctypes.c_int()
        assert lib.MXGetVersion(ctypes.byref(ver)) == 0
        assert ver.value == 10900

        handle = ctypes.c_void_p()
        keys = (ctypes.c_char_p * 1)(b"data")
        indptr = (ctypes.c_uint * 2)(0, 2)
        shape = (ctypes.c_uint * 2)(2, 4)
        rc = lib.MXPredCreate(sym.encode(), params.encode(), 1, 0, 1,
                              keys, indptr, shape, ctypes.byref(handle))
        assert rc == 0, lib.MXGetLastError()

        data = x.asnumpy().reshape(-1)
        buf = (ctypes.c_float * data.size)(*data.tolist())
        assert lib.MXPredSetInput(handle, b"data", buf, data.size) == 0, \
            lib.MXGetLastError()
        assert lib.MXPredForward(handle) == 0, lib.MXGetLastError()

        n_out = ctypes.c_uint()
        assert lib.MXPredGetNumOutputs(handle, ctypes.byref(n_out)) == 0
        assert n_out.value == 1

        sh_data = ctypes.POINTER(ctypes.c_uint)()
        sh_ndim = ctypes.c_uint()
        assert lib.MXPredGetOutputShape(handle, 0, ctypes.byref(sh_data),
                                        ctypes.byref(sh_ndim)) == 0
        shape_out = tuple(sh_data[i] for i in range(sh_ndim.value))
        assert shape_out == (2, 3)

        n = 6
        out = (ctypes.c_float * n)()
        assert lib.MXPredGetOutput(handle, 0, out, n) == 0, \
            lib.MXGetLastError()
        got = onp.asarray(list(out), onp.float32).reshape(2, 3)
        onp.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        assert lib.MXPredFree(handle) == 0

    def test_error_surface(self, tmp_path):
        _build_lib()
        lib = ctypes.CDLL(LIB)
        lib.MXGetLastError.restype = ctypes.c_char_p
        handle = ctypes.c_void_p()
        keys = (ctypes.c_char_p * 1)(b"data")
        indptr = (ctypes.c_uint * 2)(0, 1)
        shape = (ctypes.c_uint * 1)(4)
        rc = lib.MXPredCreate(b"/nonexistent-symbol.json", b"", 1, 0, 1,
                              keys, indptr, shape, ctypes.byref(handle))
        assert rc == -1
        assert len(lib.MXGetLastError()) > 0


C_HOST = r"""
#include <stdio.h>
#include <stdlib.h>
typedef unsigned int mx_uint;
typedef void* PredictorHandle;
#ifdef __cplusplus
extern "C" {
#endif
extern const char* MXGetLastError();
extern int MXGetVersion(int*);
extern int MXPredCreate(const char*, const char*, int, int, mx_uint,
                        const char**, const mx_uint*, const mx_uint*,
                        PredictorHandle*);
extern int MXPredSetInput(PredictorHandle, const char*, const float*,
                          mx_uint);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutputShape(PredictorHandle, mx_uint, mx_uint**,
                                mx_uint*);
extern int MXPredGetOutput(PredictorHandle, mx_uint, float*, mx_uint);
extern int MXPredFree(PredictorHandle);
#ifdef __cplusplus
}
#endif

#define CHECK(x) if ((x) != 0) { \
    fprintf(stderr, "FAIL: %s\n", MXGetLastError()); return 1; }

int main(int argc, char** argv) {
  int ver; CHECK(MXGetVersion(&ver));
  printf("version=%d\n", ver);
  const char* keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {2, 4};
  PredictorHandle h;
  CHECK(MXPredCreate(argv[1], argv[2], 1, 0, 1, keys, indptr, shape, &h));
  float in[8];
  for (int i = 0; i < 8; ++i) in[i] = 0.125f * i;
  CHECK(MXPredSetInput(h, "data", in, 8));
  CHECK(MXPredForward(h));
  mx_uint *sh, ndim;
  CHECK(MXPredGetOutputShape(h, 0, &sh, &ndim));
  printf("ndim=%u shape=%u,%u\n", ndim, sh[0], sh[1]);
  float out[6];
  CHECK(MXPredGetOutput(h, 0, out, 6));
  printf("out=");
  for (int i = 0; i < 6; ++i) printf("%.6f ", out[i]);
  printf("\n");
  CHECK(MXPredFree(h));
  printf("C_HOST_OK\n");
  return 0;
}
"""


class TestStandaloneCHost:
    def test_compiled_c_program(self, tmp_path):
        """Compile a real C host with g++, link libmxtpu_capi.so, run it
        in a fresh process (its own embedded interpreter), and check the
        output matches the python-side predictor."""
        _build_lib()
        sym, params, _x = _export_model(tmp_path)
        src = tmp_path / "host.c"
        src.write_text(C_HOST)
        exe = tmp_path / "host"
        libdir = os.path.dirname(LIB)
        subprocess.run(
            ["g++", str(src), "-o", str(exe), f"-L{libdir}",
             "-lmxtpu_capi", f"-Wl,-rpath,{libdir}"],
            check=True, capture_output=True, text=True)

        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run([str(exe), sym, params],
                              capture_output=True, text=True, env=env,
                              timeout=300)
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-1500:])
        assert "C_HOST_OK" in proc.stdout
        assert "version=10900" in proc.stdout
        assert "ndim=2 shape=2,3" in proc.stdout

        # cross-check values against the python predictor
        from mxnet_tpu.predictor import Predictor
        pred = Predictor(sym, params, {"data": (2, 4)})
        x = (onp.arange(8, dtype=onp.float32) * 0.125).reshape(2, 4)
        pred.set_input("data", x)
        pred.run()
        ref = pred.get_output(0).asnumpy().reshape(-1)
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("out=")][0]
        got = onp.asarray([float(v) for v in line[4:].split()],
                          onp.float32)
        onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
