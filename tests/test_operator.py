"""Op-level golden tests vs NumPy + finite-difference gradient checks.

Mirrors the reference test strategy (SURVEY.md §7):
``tests/python/unittest/test_operator.py`` — golden vs numpy,
``check_numeric_gradient``."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient, rand_ndarray)


class TestElemwise:
    def test_unary_golden(self):
        x = onp.random.uniform(0.1, 2.0, (3, 4)).astype("float32")
        a = nd.array(x)
        for name, ref in [("exp", onp.exp), ("log", onp.log),
                          ("sqrt", onp.sqrt), ("square", onp.square),
                          ("abs", onp.abs), ("sign", onp.sign),
                          ("floor", onp.floor), ("ceil", onp.ceil),
                          ("sin", onp.sin), ("cos", onp.cos),
                          ("tanh", onp.tanh)]:
            out = getattr(nd, name)(a)
            assert_almost_equal(out, ref(x), rtol=1e-4, atol=1e-4,
                                names=(name, "numpy"))

    def test_binary_broadcast(self):
        x = onp.random.randn(3, 1, 4).astype("float32")
        y = onp.random.randn(1, 5, 4).astype("float32")
        a, b = nd.array(x), nd.array(y)
        assert_almost_equal(nd.broadcast_add(a, b), x + y)
        assert_almost_equal(nd.broadcast_mul(a, b), x * y)
        assert_almost_equal(nd.broadcast_maximum(a, b), onp.maximum(x, y))
        assert_almost_equal(a * 2 + 1 - b / 2, x * 2 + 1 - y / 2)

    def test_comparison_dtype(self):
        a = nd.array([1.0, 2.0, 3.0])
        b = nd.array([2.0, 2.0, 2.0])
        out = a > b
        assert out.dtype == onp.float32
        assert_almost_equal(out, [0.0, 0.0, 1.0])

    def test_scalar_ops(self):
        a = nd.array([1.0, -2.0])
        assert_almost_equal(2.0 - a, [1.0, 4.0])
        assert_almost_equal(1.0 / a, [1.0, -0.5])
        assert_almost_equal(a ** 2, [1.0, 4.0])

    def test_clip_where(self):
        x = onp.random.randn(4, 4).astype("float32")
        assert_almost_equal(nd.clip(nd.array(x), a_min=-0.5, a_max=0.5),
                            onp.clip(x, -0.5, 0.5))
        c = (x > 0).astype("float32")
        assert_almost_equal(
            nd.where(nd.array(c), nd.array(x), nd.array(-x)), onp.abs(x))


class TestReduce:
    def test_reductions(self):
        x = onp.random.randn(2, 3, 4).astype("float32")
        a = nd.array(x)
        assert_almost_equal(nd.sum(a), x.sum())
        assert_almost_equal(nd.sum(a, axis=1), x.sum(1))
        assert_almost_equal(nd.sum(a, axis=(0, 2), keepdims=True),
                            x.sum((0, 2), keepdims=True))
        assert_almost_equal(nd.mean(a, axis=-1), x.mean(-1))
        assert_almost_equal(nd.max(a, axis=0), x.max(0))
        assert_almost_equal(nd.min(a), x.min())
        assert_almost_equal(nd.prod(a, axis=2), x.prod(2))
        assert_almost_equal(nd.norm(a), onp.sqrt((x ** 2).sum()),
                            rtol=1e-4, atol=1e-4)

    def test_sum_exclude(self):
        x = onp.random.randn(2, 3, 4).astype("float32")
        out = nd.sum(nd.array(x), axis=1, exclude=True)
        assert_almost_equal(out, x.sum((0, 2)))

    def test_argmax_argmin(self):
        x = onp.random.randn(3, 5).astype("float32")
        assert_almost_equal(nd.argmax(nd.array(x), axis=1),
                            onp.argmax(x, 1).astype("float32"))
        assert_almost_equal(nd.argmin(nd.array(x), axis=0),
                            onp.argmin(x, 0).astype("float32"))


class TestOrdering:
    def test_topk(self):
        x = onp.random.randn(4, 10).astype("float32")
        v = nd.topk(nd.array(x), k=3, ret_typ="value")
        ref = -onp.sort(-x, axis=-1)[:, :3]
        assert_almost_equal(v, ref)

    def test_sort_argsort(self):
        x = onp.random.randn(5, 6).astype("float32")
        assert_almost_equal(nd.sort(nd.array(x)), onp.sort(x))
        assert_almost_equal(nd.sort(nd.array(x), is_ascend=False),
                            -onp.sort(-x))
        assert_almost_equal(nd.argsort(nd.array(x)),
                            onp.argsort(x).astype("float32"))


class TestLinalg:
    def test_dot(self):
        a = onp.random.randn(3, 4).astype("float32")
        b = onp.random.randn(4, 5).astype("float32")
        assert_almost_equal(nd.dot(nd.array(a), nd.array(b)), a @ b,
                            rtol=1e-4, atol=1e-5)
        assert_almost_equal(
            nd.dot(nd.array(a.T), nd.array(b), transpose_a=True), a @ b,
            rtol=1e-4, atol=1e-5)
        assert_almost_equal(
            nd.dot(nd.array(a), nd.array(b.T), transpose_b=True), a @ b,
            rtol=1e-4, atol=1e-5)

    def test_dot_nd(self):
        a = onp.random.randn(2, 3, 4).astype("float32")
        b = onp.random.randn(4, 5).astype("float32")
        assert_almost_equal(nd.dot(nd.array(a), nd.array(b)),
                            onp.tensordot(a, b, axes=([-1], [0])),
                            rtol=1e-4, atol=1e-5)

    def test_batch_dot(self):
        a = onp.random.randn(6, 3, 4).astype("float32")
        b = onp.random.randn(6, 4, 5).astype("float32")
        assert_almost_equal(nd.batch_dot(nd.array(a), nd.array(b)), a @ b,
                            rtol=1e-4, atol=1e-5)
        assert_almost_equal(
            nd.batch_dot(nd.array(a), nd.array(b.transpose(0, 2, 1)),
                         transpose_b=True), a @ b, rtol=1e-4, atol=1e-5)


class TestShape:
    def test_reshape_codes(self):
        x = nd.zeros((2, 3, 4))
        assert nd.reshape(x, shape=(6, 4)).shape == (6, 4)
        assert nd.reshape(x, shape=(0, -1)).shape == (2, 12)
        assert nd.reshape(x, shape=(-2,)).shape == (2, 3, 4)
        assert nd.reshape(x, shape=(-3, 4)).shape == (6, 4)
        assert nd.reshape(x, shape=(-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)

    def test_transpose_etc(self):
        x = onp.random.randn(2, 3, 4).astype("float32")
        a = nd.array(x)
        assert_almost_equal(a.T, x.transpose())
        assert_almost_equal(nd.transpose(a, axes=(1, 0, 2)),
                            x.transpose(1, 0, 2))
        assert_almost_equal(nd.swapaxes(a, dim1=0, dim2=2), x.swapaxes(0, 2))
        assert_almost_equal(nd.expand_dims(a, axis=1),
                            onp.expand_dims(x, 1))
        assert_almost_equal(nd.flip(a, axis=2), onp.flip(x, 2))

    def test_concat_stack_split(self):
        x = onp.random.randn(2, 3).astype("float32")
        y = onp.random.randn(2, 3).astype("float32")
        assert_almost_equal(nd.concat(nd.array(x), nd.array(y), dim=1),
                            onp.concatenate([x, y], 1))
        assert_almost_equal(nd.stack(nd.array(x), nd.array(y), axis=0),
                            onp.stack([x, y]))
        parts = nd.split(nd.array(x), num_outputs=3, axis=1)
        assert len(parts) == 3 and parts[0].shape == (2, 1)

    def test_slice_ops(self):
        x = onp.arange(24).reshape(2, 3, 4).astype("float32")
        a = nd.array(x)
        assert_almost_equal(nd.slice(a, begin=(0, 1), end=(2, 3)),
                            x[0:2, 1:3])
        assert_almost_equal(nd.slice_axis(a, axis=2, begin=1, end=3),
                            x[:, :, 1:3])
        assert_almost_equal(a[1], x[1])
        assert_almost_equal(a[:, 1:2], x[:, 1:2])

    def test_tile_repeat_pad(self):
        x = onp.arange(6).reshape(2, 3).astype("float32")
        a = nd.array(x)
        assert_almost_equal(nd.tile(a, reps=(2, 2)), onp.tile(x, (2, 2)))
        assert_almost_equal(nd.repeat(a, repeats=2, axis=1),
                            onp.repeat(x, 2, 1))
        assert_almost_equal(
            nd.pad(a.reshape(1, 1, 2, 3), mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 1, 2, 2)),
            onp.pad(x.reshape(1, 1, 2, 3), ((0, 0), (0, 0), (1, 1), (2, 2))))


class TestIndexing:
    def test_take_pick(self):
        x = onp.random.randn(5, 4).astype("float32")
        idx = onp.array([0, 2, 4])
        assert_almost_equal(nd.take(nd.array(x), nd.array(idx)), x[idx])
        pidx = onp.array([0, 1, 2, 3, 0])
        assert_almost_equal(
            nd.pick(nd.array(x), nd.array(pidx.astype("float32")), axis=1),
            x[onp.arange(5), pidx])

    def test_one_hot(self):
        out = nd.one_hot(nd.array([0.0, 2.0]), depth=3)
        assert_almost_equal(out, [[1, 0, 0], [0, 0, 1]])

    def test_gather_scatter(self):
        x = onp.random.randn(3, 4).astype("float32")
        ind = onp.array([[0, 2], [1, 3]])
        out = nd.gather_nd(nd.array(x), nd.array(ind))
        assert_almost_equal(out, x[ind[0], ind[1]])

    def test_advanced_index_grad(self):
        x = nd.array(onp.arange(6, dtype="float32"))
        x.attach_grad()
        with mx.autograd.record():
            y = (x[nd.array([1, 3])] * 2).sum()
        y.backward()
        assert_almost_equal(x.grad, [0, 2, 0, 2, 0, 0])


class TestSequence:
    def test_sequence_mask(self):
        x = onp.ones((4, 2, 3), "float32")
        out = nd.SequenceMask(nd.array(x), nd.array([2.0, 3.0]),
                              use_sequence_length=True, value=-1.0)
        ref = x.copy()
        ref[2:, 0] = -1
        ref[3:, 1] = -1
        assert_almost_equal(out, ref)

    def test_sequence_last(self):
        x = onp.random.randn(4, 2, 3).astype("float32")
        out = nd.SequenceLast(nd.array(x), nd.array([2.0, 4.0]),
                              use_sequence_length=True)
        assert_almost_equal(out, onp.stack([x[1, 0], x[3, 1]]))

    def test_sequence_reverse(self):
        x = onp.arange(8).reshape(4, 2, 1).astype("float32")
        out = nd.SequenceReverse(nd.array(x), nd.array([2.0, 4.0]),
                                 use_sequence_length=True)
        assert_almost_equal(out[:, 0, 0], [2, 0, 4, 6])
        assert_almost_equal(out[:, 1, 0], [7, 5, 3, 1])


class TestGradients:
    def test_numeric_gradients(self):
        a = onp.random.uniform(0.5, 1.5, (3, 4))
        b = onp.random.uniform(0.5, 1.5, (3, 4))
        check_numeric_gradient(lambda x: (x * x).sum(), [a])
        check_numeric_gradient(lambda x: nd.exp(x).sum(), [a])
        check_numeric_gradient(lambda x, y: (x * y + x / y).sum(), [a, b])
        check_numeric_gradient(
            lambda x: nd.sum(nd.sigmoid(x) * nd.tanh(x)), [a])

    def test_dot_grad(self):
        a = onp.random.randn(3, 4) * 0.5
        b = onp.random.randn(4, 2) * 0.5
        check_numeric_gradient(lambda x, y: nd.dot(x, y).sum(), [a, b])

    def test_softmax_grad(self):
        a = onp.random.randn(2, 5)
        check_numeric_gradient(
            lambda x: (nd.softmax(x) * nd.softmax(x)).sum(), [a])

    def test_concat_split_grad(self):
        a = onp.random.randn(2, 3)
        b = onp.random.randn(2, 3)
        def f(x, y):
            c = nd.concat(x, y, dim=1)
            parts = nd.split(c, num_outputs=2, axis=1)
            return (parts[0] * parts[1]).sum()
        check_numeric_gradient(f, [a, b])

    def test_blockgrad(self):
        x = nd.array([1.0, 2.0])
        x.attach_grad()
        with mx.autograd.record():
            y = (nd.BlockGrad(x * 2) * x).sum()
        y.backward()
        assert_almost_equal(x.grad, [2.0, 4.0])


class TestCreation:
    def test_creation(self):
        assert_almost_equal(nd.zeros((2, 2)), onp.zeros((2, 2)))
        assert_almost_equal(nd.ones((2, 2)), onp.ones((2, 2)))
        assert_almost_equal(nd.full((2,), 3.0), [3.0, 3.0])
        assert_almost_equal(nd.arange(0, 5), onp.arange(5, dtype="float32"))
        assert nd.eye(3).shape == (3, 3)
        x = nd.array([[1, 2]], dtype="int32")
        assert x.dtype == onp.int32
        assert_almost_equal(nd.ones_like(x), [[1, 1]])

    def test_float64_input_becomes_f32(self):
        x = nd.array(onp.zeros((2,), onp.float64))
        assert x.dtype == onp.float32


class TestBNHandWrittenBackward:
    """r4: _BatchNormStats backward is the hand-written two-pass closed
    form — it must match autodiff of the forward math exactly (both
    training and global-stats modes, fix_gamma on/off)."""

    @pytest.mark.parametrize("training,fix_gamma", [
        (True, True), (True, False), (False, True), (False, False)])
    def test_grad_matches_autodiff(self, training, fix_gamma):
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops.nn import _bn_stats_core, _bn_stats_fwd_math
        rng = onp.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 3, 5, 5), jnp.float32)
        gamma = jnp.asarray(rng.rand(3) + 0.5, jnp.float32)
        beta = jnp.asarray(rng.randn(3), jnp.float32)
        mm = jnp.asarray(rng.randn(3) * 0.1, jnp.float32)
        mv = jnp.asarray(rng.rand(3) + 0.5, jnp.float32)
        args = (1e-5, 0.9, fix_gamma, False, 1, training)

        def loss_custom(x, g, b):
            out = _bn_stats_core(x, g, b, mm, mv, *args)[0]
            return jnp.sum(out * out)

        def loss_auto(x, g, b):
            out = _bn_stats_fwd_math(x, g, b, mm, mv, *args)[0]
            return jnp.sum(out * out)

        gc = jax.grad(loss_custom, argnums=(0, 1, 2))(x, gamma, beta)
        ga = jax.grad(loss_auto, argnums=(0, 1, 2))(x, gamma, beta)
        for c, a, nm in zip(gc, ga, ("dx", "dgamma", "dbeta")):
            onp.testing.assert_allclose(onp.asarray(c), onp.asarray(a),
                                        rtol=2e-4, atol=2e-5, err_msg=nm)
