"""Data I/O stack tests: recordio, mx.io iterators, gluon.data, mx.image.

Mirrors the reference's ``tests/python/unittest/test_recordio.py``,
``test_io.py``, ``test_gluon_data.py`` coverage (SURVEY.md §4 test strategy).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio
from mxnet_tpu.gluon.data import (ArrayDataset, SimpleDataset, DataLoader,
                                  BatchSampler, SequentialSampler,
                                  RandomSampler, IntervalSampler,
                                  FilterSampler, RecordFileDataset)
from mxnet_tpu.gluon.data.vision import (MNIST, FashionMNIST, CIFAR10,
                                         ImageRecordDataset, transforms as T)


@pytest.fixture
def rec_file(tmp_path):
    rec = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    rng = onp.random.RandomState(0)
    for i in range(8):
        img = (rng.rand(20, 24, 3) * 255).astype(onp.uint8)
        w.write_idx(i, rio.pack_img(rio.IRHeader(0, float(i % 3), i, 0), img))
    w.close()
    return rec


class TestRecordIO:
    def test_sequential_roundtrip(self, tmp_path):
        path = str(tmp_path / "seq.rec")
        payloads = [bytes([i]) * (i * 7 + 1) for i in range(10)]
        with rio.MXRecordIO(path, "w") as w:
            for p in payloads:
                w.write(p)
        r = rio.MXRecordIO(path, "r")
        got = []
        while True:
            s = r.read()
            if s is None:
                break
            got.append(s)
        assert got == payloads

    def test_indexed_random_access(self, rec_file):
        idx = rec_file[:-4] + ".idx"
        r = rio.MXIndexedRecordIO(idx, rec_file, "r")
        assert r.keys == list(range(8))
        h, img = rio.unpack_img(r.read_idx(5))
        assert float(h.label) == 2.0
        assert img.shape == (20, 24, 3)

    def test_pack_vector_label(self):
        h = rio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
        s = rio.pack(h, b"payload")
        h2, payload = rio.unpack(s)
        assert h2.flag == 3
        onp.testing.assert_allclose(onp.asarray(h2.label), [1, 2, 3])
        assert payload == b"payload"


class TestIO:
    def test_ndarrayiter_pad_and_discard(self):
        data = onp.arange(50, dtype=onp.float32).reshape(25, 2)
        it = mx.io.NDArrayIter(data, onp.zeros(25), batch_size=10,
                               last_batch_handle="pad")
        batches = list(it)
        assert len(batches) == 3 and batches[-1].pad == 5
        it = mx.io.NDArrayIter(data, onp.zeros(25), batch_size=10,
                               last_batch_handle="discard")
        assert len(list(it)) == 2

    def test_ndarrayiter_provide(self):
        it = mx.io.NDArrayIter(onp.zeros((4, 3)), onp.zeros(4), batch_size=2)
        assert it.provide_data[0].shape == (2, 3)
        assert it.provide_data[0].name == "data"
        assert it.provide_label[0].name == "softmax_label"

    def test_resize_iter(self):
        it = mx.io.NDArrayIter(onp.zeros((6, 2)), onp.zeros(6), batch_size=2)
        r = mx.io.ResizeIter(it, 7)
        assert len(list(r)) == 7

    def test_prefetching_iter(self):
        it = mx.io.NDArrayIter(onp.arange(12, dtype=onp.float32).reshape(6, 2),
                               onp.zeros(6), batch_size=2)
        p = mx.io.PrefetchingIter(it)
        batches = list(p)
        assert len(batches) == 3
        p.reset()
        assert len(list(p)) == 3

    def test_csviter(self, tmp_path):
        data_csv = str(tmp_path / "d.csv")
        onp.savetxt(data_csv, onp.arange(12).reshape(4, 3), delimiter=",")
        it = mx.io.CSVIter(data_csv=data_csv, data_shape=(3,), batch_size=2)
        b = next(iter(it))
        assert b.data[0].shape == (2, 3)


class TestDataset:
    def test_array_dataset(self):
        ds = ArrayDataset(onp.arange(10), onp.arange(10) * 2)
        assert len(ds) == 10
        a, b = ds[3]
        assert int(a) == 3 and int(b) == 6

    def test_transform_first(self):
        ds = ArrayDataset(onp.arange(4, dtype=onp.float32), onp.arange(4))
        ds2 = ds.transform_first(lambda x: x * 10)
        x, y = ds2[2]
        assert float(x) == 20.0 and int(y) == 2

    def test_filter_shard_take(self):
        ds = SimpleDataset(list(range(10)))
        assert len(ds.filter(lambda x: x % 2 == 0)) == 5
        assert list(ds.shard(3, 0)[i] for i in range(len(ds.shard(3, 0)))) == [0, 3, 6, 9]
        assert len(ds.take(4)) == 4

    def test_record_file_dataset(self, rec_file):
        ds = RecordFileDataset(rec_file)
        assert len(ds) == 8
        h, _ = rio.unpack(ds[2])
        assert float(h.label) == 2.0

    def test_image_record_dataset(self, rec_file):
        ds = ImageRecordDataset(rec_file)
        img, label = ds[4]
        assert img.shape == (20, 24, 3)
        assert label == 1.0


class TestSampler:
    def test_sequential_random(self):
        assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
        assert sorted(RandomSampler(5)) == [0, 1, 2, 3, 4]

    def test_batch_sampler(self):
        bs = BatchSampler(SequentialSampler(7), 3, "keep")
        assert [len(b) for b in bs] == [3, 3, 1]
        bs = BatchSampler(SequentialSampler(7), 3, "discard")
        assert [len(b) for b in bs] == [3, 3]
        bs = BatchSampler(SequentialSampler(7), 3, "rollover")
        assert [len(b) for b in bs] == [3, 3]
        assert [len(b) for b in bs] == [3, 3]  # rolled-over 1 + first 2

    def test_interval_filter(self):
        assert list(IntervalSampler(6, 2)) == [0, 2, 4, 1, 3, 5]
        ds = SimpleDataset(list(range(6)))
        assert list(FilterSampler(lambda x: x > 3, ds)) == [4, 5]


class TestDataLoader:
    def test_basic(self):
        ds = ArrayDataset(onp.random.rand(20, 3).astype(onp.float32),
                          onp.arange(20, dtype=onp.float32))
        dl = DataLoader(ds, batch_size=6, last_batch="keep")
        shapes = [x.shape for x, _ in dl]
        assert shapes == [(6, 3), (6, 3), (6, 3), (2, 3)]
        assert len(dl) == 4

    def test_workers_match_serial(self):
        ds = ArrayDataset(onp.arange(30, dtype=onp.float32).reshape(10, 3),
                          onp.arange(10, dtype=onp.float32))
        serial = [x.asnumpy() for x, _ in DataLoader(ds, batch_size=5)]
        threaded = [x.asnumpy() for x, _ in DataLoader(ds, batch_size=5,
                                                       num_workers=3)]
        for a, b in zip(serial, threaded):
            onp.testing.assert_array_equal(a, b)

    def test_vision_pipeline(self):
        ds = MNIST(train=True, synthetic=32).transform_first(
            T.Compose([T.ToTensor(), T.Normalize(0.13, 0.31)]))
        xb, yb = next(iter(DataLoader(ds, batch_size=8, shuffle=True)))
        assert xb.shape == (8, 1, 28, 28)
        assert str(xb.dtype) == "float32"

    def test_cifar_synthetic(self):
        ds = CIFAR10(train=False, synthetic=16)
        x, y = ds[0]
        assert x.shape == (32, 32, 3)
        assert 0 <= y < 10


class TestImage:
    def test_imdecode_imencode_roundtrip(self):
        img = (onp.random.rand(16, 16, 3) * 255).astype(onp.uint8)
        enc = mx.image.imencode(img, img_fmt=".png")
        dec = mx.image.imdecode(enc)
        onp.testing.assert_array_equal(dec.asnumpy(), img)

    def test_resize_crop(self):
        img = mx.nd.array((onp.random.rand(20, 30, 3) * 255).astype(onp.uint8),
                          dtype="uint8")
        assert mx.image.imresize(img, 8, 10).shape == (10, 8, 3)
        assert mx.image.resize_short(img, 10).shape == (10, 15, 3)
        out, _ = mx.image.center_crop(img, (12, 12))
        assert out.shape == (12, 12, 3)
        out, _ = mx.image.random_crop(img, (8, 8))
        assert out.shape == (8, 8, 3)

    def test_augmenter_list(self):
        augs = mx.image.CreateAugmenter((3, 16, 16), rand_crop=True,
                                        rand_mirror=True, mean=True, std=True)
        img = mx.nd.array((onp.random.rand(20, 20, 3) * 255).astype(onp.uint8),
                          dtype="uint8")
        for a in augs:
            img = a(img)
        assert img.shape == (16, 16, 3)

    def test_image_iter(self, rec_file):
        it = mx.image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                                path_imgrec=rec_file, shuffle=True)
        b = it.next()
        assert b.data[0].shape == (4, 3, 16, 16)
        assert b.label[0].shape == (4,)

    def test_det_iter(self, tmp_path):
        rec = str(tmp_path / "det.rec")
        idx = str(tmp_path / "det.idx")
        w = rio.MXIndexedRecordIO(idx, rec, "w")
        rng = onp.random.RandomState(1)
        for i in range(4):
            img = (rng.rand(20, 20, 3) * 255).astype(onp.uint8)
            # label: [header_w=2, obj_w=5, cls, xmin, ymin, xmax, ymax]
            label = [2, 5, 1, 0.1, 0.1, 0.6, 0.7]
            w.write_idx(i, rio.pack_img(rio.IRHeader(0, label, i, 0), img))
        w.close()
        it = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                                   path_imgrec=rec, rand_mirror=True)
        b = it.next()
        assert b.data[0].shape == (2, 3, 16, 16)
        assert b.label[0].shape[0] == 2 and b.label[0].shape[2] == 5


def _dev_id(arr):
    return list(arr._data.devices())[0].id


class TestMultiWorkerIter:
    """Satellites: ordering, last_batch modes, explicit prefetch, early-
    break cleanup, timeout raise (ISSUE 3)."""

    def _ds(self, n=17):
        return ArrayDataset(onp.arange(3 * n, dtype=onp.float32).reshape(n, 3),
                            onp.arange(n, dtype=onp.float32))

    def test_order_matches_serial_across_worker_counts(self):
        ds = self._ds()
        serial = [x.asnumpy() for x, _ in DataLoader(ds, batch_size=4,
                                                     last_batch="keep")]
        for nw in (1, 2, 4):
            threaded = [x.asnumpy() for x, _ in
                        DataLoader(ds, batch_size=4, last_batch="keep",
                                   num_workers=nw)]
            assert len(threaded) == len(serial)
            for a, b in zip(serial, threaded):
                onp.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("last_batch,want", [("keep", 5),
                                                 ("discard", 4),
                                                 ("rollover", 4)])
    def test_last_batch_modes_with_workers(self, last_batch, want):
        dl = DataLoader(self._ds(17), batch_size=4, last_batch=last_batch,
                        num_workers=2)
        assert len([b for b in dl]) == want

    def test_explicit_prefetch_honored(self):
        it = iter(DataLoader(self._ds(), batch_size=4, num_workers=4,
                             prefetch=1))
        assert it._prefetch == 1  # not silently raised to 2*num_workers
        it2 = iter(DataLoader(self._ds(), batch_size=4, num_workers=4))
        assert it2._prefetch == 8  # default stays 2*num_workers
        it.shutdown()
        it2.shutdown()

    def test_early_break_shuts_down_executor(self):
        import gc
        dl = DataLoader(self._ds(), batch_size=2, num_workers=2)
        it = iter(dl)
        next(it)  # abandon the epoch after one batch
        executor = it._executor
        del it  # queued work items hold a bound-method cycle → needs gc
        gc.collect()
        assert executor._shutdown

    def test_timeout_raises_with_batch_index(self):
        import time as _time

        class SlowDataset(SimpleDataset):
            def __getitem__(self, idx):
                _time.sleep(1.5)
                return super().__getitem__(idx)

        dl = DataLoader(SlowDataset(list(range(8))), batch_size=2,
                        num_workers=1, timeout=0.2)
        with pytest.raises(mx.MXNetError, match="batch 0"):
            next(iter(dl))

    def test_worker_error_propagates_and_cleans_up(self):
        class BadDataset(SimpleDataset):
            def __getitem__(self, idx):
                raise ValueError("boom")

        it = iter(DataLoader(BadDataset(list(range(8))), batch_size=2,
                             num_workers=1))
        with pytest.raises(ValueError, match="boom"):
            next(it)
        assert it._executor._shutdown


class TestDevicePrefetch:
    """Tentpole: device-resident / pre-sharded prefetched batches
    (ISSUE 3).  Runs on the 8-device virtual CPU platform."""

    def _ds(self, n=16):
        return ArrayDataset(onp.arange(3 * n, dtype=onp.float32).reshape(n, 3),
                            onp.arange(n, dtype=onp.float32))

    def _serial(self, ds, bs=4):
        return [x.asnumpy() for x, _ in DataLoader(ds, batch_size=bs)]

    def test_batches_device_resident_and_bit_identical(self):
        ds = self._ds()
        ref = self._serial(ds)
        dl = DataLoader(ds, batch_size=4, device=mx.Context("cpu", 1))
        got = list(dl)
        assert len(got) == len(ref)
        for (x, y), r in zip(got, ref):
            assert _dev_id(x) == 1 and _dev_id(y) == 1
            onp.testing.assert_array_equal(x.asnumpy(), r)

    def test_multiworker_device_order_and_residency(self):
        ds = self._ds()
        ref = self._serial(ds)
        for dp in (2, 8):  # ring path (2 < prefetch) and worker-place path
            dl = DataLoader(ds, batch_size=4, num_workers=2,
                            device=mx.Context("cpu", 2), device_prefetch=dp)
            for (x, _), r in zip(dl, ref):
                assert _dev_id(x) == 2
                onp.testing.assert_array_equal(x.asnumpy(), r)

    def test_env_zero_restores_synchronous_path(self, monkeypatch):
        monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "0")
        ds = self._ds()
        dl = DataLoader(ds, batch_size=4, device=mx.Context("cpu", 1),
                        device_prefetch=4)
        it = iter(dl)
        from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter
        assert isinstance(it, DevicePrefetchIter)
        assert it._depth == 0 and it._thread is None  # no ring, no thread
        for (x, _), r in zip(it, self._serial(ds)):
            assert _dev_id(x) == 1  # placement still honored
            onp.testing.assert_array_equal(x.asnumpy(), r)

    def test_sharded_placement_over_device_list(self):
        ctxs = [mx.Context("cpu", i) for i in range(4)]
        dl = DataLoader(self._ds(), batch_size=8, device=ctxs)
        xb, yb = next(iter(dl))
        sh = xb._data.sharding
        assert len(sh.device_set) == 4 and not sh.is_fully_replicated
        shapes = {tuple(s.data.shape) for s in xb._data.addressable_shards}
        assert shapes == {(2, 3)}

    def test_split_and_load_uses_resident_shards(self):
        from mxnet_tpu.gluon.utils import split_and_load
        ctxs = [mx.Context("cpu", i) for i in range(4)]
        xb, _ = next(iter(DataLoader(self._ds(), batch_size=8, device=ctxs)))
        full = xb.asnumpy()
        parts = split_and_load(xb, ctxs)
        for i, p in enumerate(parts):
            assert _dev_id(p) == i
            onp.testing.assert_array_equal(p.asnumpy(), full[2 * i:2 * i + 2])

    def test_partial_tail_batch_replicates(self):
        ctxs = [mx.Context("cpu", i) for i in range(4)]
        batches = list(DataLoader(self._ds(14), batch_size=4, device=ctxs,
                                  last_batch="keep"))
        tail = batches[-1][0]
        assert tail.shape == (2, 3)  # 14 = 3*4 + 2
        assert tail._data.sharding.is_fully_replicated

    def test_early_break_cleans_both_layers(self):
        dl = DataLoader(self._ds(), batch_size=2, num_workers=2,
                        device=mx.Context("cpu", 1), device_prefetch=1)
        it = iter(dl)
        next(it)
        inner = it._source
        it.close()
        assert inner._closed and inner._executor._shutdown

    def test_explicit_sharding_object(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        mesh = Mesh(onp.array(jax.devices()[:2]), ("dp",))
        sh = NamedSharding(mesh, PartitionSpec("dp"))
        xb, _ = next(iter(DataLoader(self._ds(), batch_size=4, device=sh)))
        assert xb._data.sharding == sh

    def test_standalone_iter_over_plain_iterable(self):
        from mxnet_tpu.gluon.data import DevicePrefetchIter
        src = [onp.full((2, 2), i, onp.float32) for i in range(5)]
        out = list(DevicePrefetchIter(iter(src), mx.Context("cpu", 3),
                                      depth=2))
        assert len(out) == 5
        for i, x in enumerate(out):
            assert _dev_id(x) == 3
            onp.testing.assert_array_equal(x.asnumpy(), src[i])

    def test_source_error_propagates(self):
        from mxnet_tpu.gluon.data import DevicePrefetchIter

        def bad():
            yield onp.zeros((2, 2), onp.float32)
            raise RuntimeError("pipeline broke")

        it = DevicePrefetchIter(bad(), mx.Context("cpu", 0), depth=2)
        next(it)
        with pytest.raises(RuntimeError, match="pipeline broke"):
            next(it)
        with pytest.raises(StopIteration):  # terminal, must not block
            next(it)

    def test_next_after_exhaustion_raises_not_hangs(self):
        from mxnet_tpu.gluon.data import DevicePrefetchIter
        it = DevicePrefetchIter(iter([onp.zeros((2,), onp.float32)]),
                                mx.Context("cpu", 0), depth=2)
        assert len(list(it)) == 1
        for _ in range(2):  # repeated next() past the single end marker
            with pytest.raises(StopIteration):
                next(it)

    def test_io_prefetching_iter_producer_error_propagates(self):
        class BadIter(mx.io.DataIter):
            def next(self):
                raise RuntimeError("decode failed")

        p = mx.io.PrefetchingIter(BadIter(batch_size=2),
                                  device=mx.Context("cpu", 1))
        with pytest.raises(RuntimeError, match="decode failed"):
            p.next()

    def test_io_env_zero_keeps_hostside_thread_without_device(self,
                                                              monkeypatch):
        monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "0")
        it = mx.io.NDArrayIter(
            onp.arange(12, dtype=onp.float32).reshape(6, 2), onp.zeros(6),
            batch_size=2)
        p = mx.io.PrefetchingIter(it)  # no device: escape hatch inert
        assert not p._sync and p._thread is not None
        assert len(list(p)) == 3

    def test_io_prefetching_iter_device(self):
        it = mx.io.NDArrayIter(
            onp.arange(12, dtype=onp.float32).reshape(6, 2), onp.zeros(6),
            batch_size=2)
        p = mx.io.PrefetchingIter(it, device=mx.Context("cpu", 5))
        bs = list(p)
        assert len(bs) == 3
        assert all(_dev_id(b.data[0]) == 5 for b in bs)
        p.reset()
        assert len(list(p)) == 3

    def test_io_prefetching_iter_env_zero_sync(self, monkeypatch):
        monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "0")
        it = mx.io.NDArrayIter(
            onp.arange(12, dtype=onp.float32).reshape(6, 2), onp.zeros(6),
            batch_size=2)
        p = mx.io.PrefetchingIter(it, device=mx.Context("cpu", 4))
        assert p._sync and p._thread is None
        bs = list(p)
        assert len(bs) == 3 and all(_dev_id(b.data[0]) == 4 for b in bs)

    def test_estimator_wraps_epoch_iterator(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon.contrib.estimator import Estimator
        from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter
        net = gluon.nn.Dense(2, in_units=3)
        data = [(onp.ones((2, 3), onp.float32), onp.zeros((2, 2), onp.float32))]
        # accelerator context (degrades to host device here): ring engaged
        est = Estimator(net, gluon.loss.L2Loss(),
                        context=mx.Context("tpu", 0))
        it = est._prefetched(data)
        assert isinstance(it, DevicePrefetchIter)
        batches = list(it)
        assert len(batches) == 1 and isinstance(batches[0][0], mx.nd.NDArray)
        # host context: inert, plain iteration
        est2 = Estimator(net, gluon.loss.L2Loss(),
                         context=mx.Context("cpu", 0))
        assert not isinstance(est2._prefetched(data), DevicePrefetchIter)

    def test_nd_array_ctx_single_hop(self):
        a = mx.nd.array(onp.arange(6, dtype=onp.int64), ctx=mx.Context("cpu", 3))
        assert str(a.dtype) == "int32" and _dev_id(a) == 3  # canonicalized
        b = mx.nd.array([1.5, 2.5], ctx=mx.Context("cpu", 2))
        assert str(b.dtype) == "float32" and _dev_id(b) == 2


class TestInputPipelineBenchSmoke:
    """The overlap measurement can't bit-rot: --smoke runs the h2d stage
    at tiny sizes with no PIL/native dependency (ISSUE 3 CI satellite)."""

    def test_smoke_mode_emits_overlap_rows(self, capsys):
        import json
        import benchmark.input_pipeline_bench as bench
        assert bench.main(["--smoke"]) == 0
        rows = [json.loads(l) for l in
                capsys.readouterr().out.strip().splitlines()]
        stages = {r["stage"] for r in rows}
        assert {"h2d_input_only", "h2d_compute_only", "h2d_step_sync",
                "h2d_step_overlap"} <= stages
        overlap = next(r for r in rows if r["stage"] == "h2d_step_overlap")
        assert overlap["ms_per_step"] > 0 and overlap["speedup_vs_sync"] > 0


class TestBatchify:
    def test_pad_variable_lengths(self):
        from mxnet_tpu.gluon.data import batchify
        seqs = [onp.arange(3), onp.arange(5), onp.arange(2)]
        out, lens = batchify.Pad(pad_val=-1, ret_length=True)(seqs)
        assert out.shape == (3, 5)
        onp.testing.assert_array_equal(lens.asnumpy(), [3, 5, 2])
        onp.testing.assert_array_equal(out.asnumpy()[2], [0, 1, -1, -1, -1])

    def test_tuple_composition_with_loader(self):
        from mxnet_tpu.gluon.data import ArrayDataset, DataLoader, batchify
        seqs = [onp.arange(n, dtype=onp.float32) for n in (2, 4, 3, 5)]
        labels = onp.arange(4, dtype=onp.float32)
        ds = ArrayDataset(seqs, labels)
        fn = batchify.Tuple(batchify.Pad(), batchify.Stack())
        xb, yb = next(iter(DataLoader(ds, batch_size=4, batchify_fn=fn)))
        assert xb.shape == (4, 5)
        assert yb.shape == (4,)

    def test_stack_casts_64bit(self):
        from mxnet_tpu.gluon.data import batchify
        out = batchify.Stack()([onp.array([1, 2]), onp.array([3, 4])])
        assert str(out.dtype) in ("int32", "int64")


class TestIteratorConcurrency:
    """Regression net for the TL004 lock discipline (ISSUE 5 satellite):
    hammer concurrent ``next()`` + ``shutdown()``/``close()`` from
    multiple threads — no deadlock, no IndexError off the shared deque,
    no leaked executor, no consumer stranded in ``queue.get()``."""

    def _consume(self, it, errs):
        from concurrent.futures import CancelledError
        from mxnet_tpu.base import MXNetError
        try:
            while True:
                try:
                    next(it)
                except StopIteration:
                    return
        except (CancelledError, MXNetError):
            return  # a future cancelled / timed out by shutdown is fine
        except BaseException as e:  # noqa: BLE001 — recorded for assert
            errs.append(e)

    def _hammer(self, make_iter, closer, rounds=12, consumers=2):
        import threading
        import time
        for i in range(rounds):
            it = make_iter()
            errs = []
            threads = [threading.Thread(target=self._consume,
                                        args=(it, errs), daemon=True)
                       for _ in range(consumers)]
            for t in threads:
                t.start()
            # vary the interleaving: sometimes mid-epoch, sometimes late
            time.sleep(0.001 * (i % 4))
            closer(it)
            for t in threads:
                t.join(timeout=10)
            assert not any(t.is_alive() for t in threads), \
                f"round {i}: consumer thread deadlocked after close"
            assert not errs, f"round {i}: {errs!r}"
            yield it

    def test_multiworker_next_vs_shutdown(self):
        ds = SimpleDataset(list(range(64)))
        def make():
            return iter(DataLoader(ds, batch_size=4, num_workers=2,
                                   prefetch=3))
        for it in self._hammer(make, lambda it: it.shutdown()):
            # no executor leak: the pool must be torn down
            assert it._executor._shutdown
            # ring closed: further next() terminates, never hangs
            with pytest.raises(StopIteration):
                next(it)

    # depth=1 is the tight case: a straggler batch can fill the single
    # queue slot between close()'s drain and the producer noticing
    # _stop, so the injected _END pill must evict-and-retry, never drop
    @pytest.mark.parametrize("depth", [1, 2])
    def test_device_prefetch_next_vs_close(self, depth):
        from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter

        def make():
            def src():
                for j in range(64):
                    yield onp.full((2,), j, onp.float32)
            return DevicePrefetchIter(src(), mx.Context("cpu", 0),
                                      depth=depth)

        for it in self._hammer(make, lambda it: it.close()):
            assert it._thread is None  # producer joined, not leaked
            with pytest.raises(StopIteration):
                next(it)

    def test_stacked_loader_close_midway(self):
        """DataLoader(num_workers>0, device=...) stacks the device ring
        over the worker pool; breaking out mid-epoch must unwind BOTH
        layers from __del__/close without deadlock."""
        from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter
        ds = SimpleDataset(list(range(48)))
        for _ in range(6):
            loader = DataLoader(ds, batch_size=4, num_workers=2,
                                device=mx.Context("cpu", 0),
                                device_prefetch=1, prefetch=4)
            it = iter(loader)
            assert isinstance(it, DevicePrefetchIter)
            next(it)
            inner = it._source
            it.close()
            assert inner._executor._shutdown
