"""Round-2 operator-corpus extensions (mxnet_tpu/ops/extended.py):
golden numerics vs numpy + selected gradient checks."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def _nd(a):
    return mx.nd.array(onp.asarray(a))


class TestSpatialOps:
    def test_spatial_transformer_identity(self):
        """Identity affine theta must reproduce the input."""
        rng = onp.random.RandomState(0)
        img = rng.rand(2, 3, 8, 8).astype(onp.float32)
        theta = onp.tile(onp.array([1, 0, 0, 0, 1, 0], onp.float32),
                         (2, 1))
        out = mx.nd.SpatialTransformer(_nd(img), _nd(theta),
                                       target_shape=(8, 8))
        onp.testing.assert_allclose(out.asnumpy(), img, rtol=1e-4,
                                    atol=1e-4)

    def test_spatial_transformer_zoom(self):
        """0.5-scale theta samples the center crop (smoke + shape)."""
        rng = onp.random.RandomState(1)
        img = rng.rand(1, 1, 8, 8).astype(onp.float32)
        theta = onp.array([[0.5, 0, 0, 0, 0.5, 0]], onp.float32)
        out = mx.nd.SpatialTransformer(_nd(img), _nd(theta),
                                       target_shape=(4, 4))
        assert out.shape == (1, 1, 4, 4)
        assert onp.isfinite(out.asnumpy()).all()

    def test_lrn_formula(self):
        rng = onp.random.RandomState(2)
        x = rng.rand(1, 6, 3, 3).astype(onp.float32)
        out = mx.nd.LRN(_nd(x), alpha=1e-3, beta=0.75, knorm=2.0, nsize=3)
        # reference formula, dense loop
        ref = onp.empty_like(x)
        for c in range(6):
            lo, hi = max(0, c - 1), min(6, c + 2)
            s = (x[:, lo:hi] ** 2).sum(axis=1)
            ref[:, c] = x[:, c] / (2.0 + 1e-3 / 3 * s) ** 0.75
        onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5,
                                    atol=1e-6)


class TestIndexing:
    def test_batch_take(self):
        a = onp.arange(12, dtype=onp.float32).reshape(4, 3)
        idx = onp.array([0, 2, 1, 0], onp.float32)
        out = mx.nd.batch_take(_nd(a), _nd(idx))
        onp.testing.assert_array_equal(out.asnumpy(),
                                       a[onp.arange(4), idx.astype(int)])

    def test_ravel_unravel_roundtrip(self):
        coords = onp.array([[1, 2, 0], [0, 3, 1]], onp.int64)  # (2, 3)
        flat = mx.nd.ravel_multi_index(_nd(coords).astype("int64"),
                                       shape=(3, 4))
        onp.testing.assert_array_equal(
            flat.asnumpy(), onp.ravel_multi_index(coords, (3, 4)))
        back = mx.nd.unravel_index(flat, shape=(3, 4))
        onp.testing.assert_array_equal(back.asnumpy(), coords)

    def test_index_array(self):
        x = mx.nd.zeros((2, 3))
        out = mx.nd.index_array(x)
        assert out.shape == (2, 3, 2)
        onp.testing.assert_array_equal(out.asnumpy()[1, 2], [1, 2])

    def test_searchsorted_and_unique(self):
        a = onp.array([1.0, 3.0, 5.0], onp.float32)
        v = onp.array([2.0, 5.0], onp.float32)
        out = mx.nd.searchsorted(_nd(a), _nd(v))
        onp.testing.assert_array_equal(out.asnumpy(), [1, 2])
        u = mx.nd.unique_op(_nd(onp.array([3.0, 1.0, 3.0, 2.0],
                                          onp.float32)), size=3)
        onp.testing.assert_array_equal(u.asnumpy(), [1.0, 2.0, 3.0])


class TestMaskedSoftmax:
    def test_masked_softmax_matches_manual(self):
        rng = onp.random.RandomState(3)
        x = rng.rand(2, 5).astype(onp.float32)
        mask = onp.array([[1, 1, 0, 1, 0], [1, 1, 1, 1, 1]], onp.float32)
        out = mx.nd.masked_softmax(_nd(x), _nd(mask))
        arr = out.asnumpy()
        assert (arr[0, [2, 4]] == 0).all()
        onp.testing.assert_allclose(arr.sum(-1), [1.0, 1.0], rtol=1e-5)

    def test_masked_softmax_grad_flows(self):
        x = _nd(onp.random.RandomState(4).rand(2, 4).astype(onp.float32))
        mask = _nd(onp.array([[1, 1, 1, 0]] * 2, onp.float32))
        x.attach_grad()
        with autograd.record():
            out = mx.nd.masked_softmax(x, mask)
            loss = (out * out).sum()
        loss.backward()
        g = x.grad.asnumpy()
        assert onp.isfinite(g).all()
        onp.testing.assert_allclose(g[:, 3], 0.0, atol=1e-6)


class TestNumpyParityOps:
    """Golden one-liners vs numpy for the breadth additions."""

    CASES = [
        ("cumsum", lambda: (onp.arange(6.0).reshape(2, 3),), {"axis": 1},
         lambda a: onp.cumsum(a, axis=1)),
        ("cumprod", lambda: (onp.arange(1.0, 7.0).reshape(2, 3),),
         {"axis": 0}, lambda a: onp.cumprod(a, axis=0)),
        ("diff", lambda: (onp.array([1.0, 3.0, 6.0, 10.0]),), {},
         lambda a: onp.diff(a)),
        ("tril", lambda: (onp.ones((3, 3), onp.float32),), {"k": 0},
         onp.tril),
        ("triu", lambda: (onp.ones((3, 3), onp.float32),), {"k": 1},
         lambda a: onp.triu(a, 1)),
        ("trace", lambda: (onp.arange(9.0).reshape(3, 3),), {},
         lambda a: onp.trace(a)),
        ("kron", lambda: (onp.eye(2, dtype=onp.float32),
                          onp.ones((2, 2), onp.float32)), {}, onp.kron),
        ("outer", lambda: (onp.arange(3.0), onp.arange(2.0)), {},
         onp.outer),
        ("hypot", lambda: (onp.array([3.0]), onp.array([4.0])), {},
         onp.hypot),
        ("logaddexp", lambda: (onp.array([1.0]), onp.array([2.0])), {},
         onp.logaddexp),
        ("copysign", lambda: (onp.array([1.0, -2.0]),
                              onp.array([-1.0, 1.0])), {}, onp.copysign),
        ("var", lambda: (onp.arange(8.0),), {}, lambda a: onp.var(a)),
        ("std", lambda: (onp.arange(8.0),), {}, lambda a: onp.std(a)),
        ("median", lambda: (onp.array([3.0, 1.0, 2.0]),), {},
         lambda a: onp.median(a)),
        ("ptp", lambda: (onp.array([3.0, 1.0, 7.0]),), {},
         lambda a: onp.ptp(a)),
        ("roll", lambda: (onp.arange(5.0),), {"shift": 2},
         lambda a: onp.roll(a, 2)),
        ("rot90", lambda: (onp.arange(4.0).reshape(2, 2),), {},
         lambda a: onp.rot90(a)),
        ("fliplr", lambda: (onp.arange(4.0).reshape(2, 2),), {},
         onp.fliplr),
        ("flipud", lambda: (onp.arange(4.0).reshape(2, 2),), {},
         onp.flipud),
        ("nan_to_num",
         lambda: (onp.array([onp.nan, 1.0, onp.inf], onp.float32),), {},
         lambda a: onp.nan_to_num(a)),
        ("squared_difference", lambda: (onp.array([3.0]),
                                        onp.array([1.0])), {},
         lambda a, b: (a - b) ** 2),
        ("digamma", lambda: (onp.array([1.0, 2.0]),), {},
         lambda a: onp.array([-0.5772157, 0.42278433], onp.float32)),
        ("logsumexp", lambda: (onp.array([1.0, 2.0, 3.0]),), {},
         lambda a: onp.log(onp.exp(a).sum())),
        ("isnan", lambda: (onp.array([1.0, onp.nan]),), {}, onp.isnan),
        ("isinf", lambda: (onp.array([1.0, onp.inf]),), {}, onp.isinf),
        ("gcd", lambda: (onp.array([12]), onp.array([8])), {}, onp.gcd),
        ("floor_divide", lambda: (onp.array([7.0]), onp.array([2.0])), {},
         lambda a, b: a // b),
        ("remainder", lambda: (onp.array([7.0]), onp.array([3.0])), {},
         onp.remainder),
    ]

    @pytest.mark.parametrize("name,mk,kw,ref",
                             CASES, ids=[c[0] for c in CASES])
    def test_golden(self, name, mk, kw, ref):
        args = mk()
        out = getattr(mx.nd, name)(*[_nd(a) for a in args], **kw)
        onp.testing.assert_allclose(out.asnumpy(), ref(*args),
                                    rtol=1e-4, atol=1e-5)

    def test_moments(self):
        rng = onp.random.RandomState(5)
        x = rng.rand(3, 4).astype(onp.float32)
        mean, var = mx.nd.moments(_nd(x), axes=(1,))
        onp.testing.assert_allclose(mean.asnumpy(), x.mean(1), rtol=1e-5)
        onp.testing.assert_allclose(var.asnumpy(), x.var(1), rtol=1e-4)

    def test_meshgrid_and_stacks(self):
        a, b = onp.arange(3.0), onp.arange(2.0)
        gx, gy = mx.nd.meshgrid(_nd(a), _nd(b))
        rx, ry = onp.meshgrid(a, b)
        onp.testing.assert_array_equal(gx.asnumpy(), rx)
        onp.testing.assert_array_equal(gy.asnumpy(), ry)
        h = mx.nd.hstack(_nd(a), _nd(a))
        onp.testing.assert_array_equal(h.asnumpy(), onp.hstack([a, a]))
        v = mx.nd.vstack(_nd(a), _nd(a))
        assert v.shape == (2, 3)

    def test_bincount_histogram(self):
        x = onp.array([0, 1, 1, 3], onp.int32)
        out = mx.nd.bincount_op(_nd(x), length=4)
        onp.testing.assert_array_equal(out.asnumpy(), [1, 2, 0, 1])
        counts, edges = mx.nd.histogram_op(
            _nd(onp.array([0.1, 0.4, 0.6], onp.float32)), bin_cnt=2,
            range=(0.0, 1.0))
        onp.testing.assert_array_equal(counts.asnumpy(), [2, 1])

    def test_khatri_rao(self):
        A = onp.array([[1.0, 2.0], [3.0, 4.0]], onp.float32)
        B = onp.array([[5.0, 6.0]], onp.float32)
        out = mx.nd.khatri_rao(_nd(A), _nd(B))
        ref = onp.vstack([onp.kron(A[:, i], B[:, i])
                          for i in range(2)]).T
        onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)

    def test_clip_global_norm_op(self):
        a = onp.full(4, 3.0, onp.float32)
        b = onp.full(4, 4.0, onp.float32)
        outs = mx.nd.clip_global_norm(_nd(a), _nd(b), max_norm=1.0)
        total = onp.sqrt(sum((x.asnumpy() ** 2).sum() for x in outs))
        onp.testing.assert_allclose(total, 1.0, rtol=1e-4)

    def test_relu6_hard_swish_grad(self):
        x = _nd(onp.array([-1.0, 3.0, 7.0], onp.float32))
        x.attach_grad()
        with autograd.record():
            loss = (mx.nd.relu6(x) + mx.nd.hard_swish(x)).sum()
        loss.backward()
        assert onp.isfinite(x.grad.asnumpy()).all()

    def test_arange_like(self):
        x = mx.nd.zeros((2, 3))
        out = mx.nd.arange_like(x)
        onp.testing.assert_array_equal(out.asnumpy(),
                                       onp.arange(6.0).reshape(2, 3))
