"""Gluon Block/HybridBlock/Parameter tests (mirrors reference
tests/python/unittest/test_gluon.py strategy, SURVEY.md §7)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(4, 3))
    p.initialize(init=mx.init.Xavier())
    assert p.data().shape == (4, 3)
    assert p.grad().shape == (4, 3)
    assert float(p.grad().asnumpy().sum()) == 0.0


def test_parameter_deferred():
    p = gluon.Parameter("weight", shape=(4, 0), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(gluon.DeferredInitializationError):
        p.data()
    p.shape = (4, 7)
    p._finish_deferred_init()
    assert p.data().shape == (4, 7)


def test_dense_forward_matches_numpy():
    net = nn.Dense(5, in_units=3, use_bias=True)
    net.initialize()
    x = mx.nd.array(onp.random.randn(2, 3).astype(onp.float32))
    out = net(x)
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    expect = x.asnumpy() @ w.T + b
    onp.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5)


def test_sequential_and_collect_params():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    x = mx.nd.array(onp.random.randn(4, 3).astype(onp.float32))
    assert net(x).shape == (4, 2)
    params = net.collect_params()
    assert len(params) == 4
    weights = net.collect_params(".*weight")
    assert len(weights) == 2


def test_hybridize_matches_imperative():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(onp.random.randn(5, 7).astype(onp.float32))
    out_imp = net(x).asnumpy()
    net.hybridize()
    out_hyb = net(x).asnumpy()
    onp.testing.assert_allclose(out_imp, out_hyb, rtol=1e-5, atol=1e-6)


def test_hybridize_grads_match():
    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
        return net

    mx.random.seed(7)
    net = build()
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0).randn(6, 4).astype(onp.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_imp = net[0].weight.grad().asnumpy().copy()

    net.hybridize()
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_hyb = net[0].weight.grad().asnumpy()
    onp.testing.assert_allclose(g_imp, g_hyb, rtol=1e-4, atol=1e-5)


def test_batchnorm_moving_stats_update():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = mx.nd.array(onp.random.randn(8, 3, 4, 4).astype(onp.float32) * 5 + 2)
    with mx.autograd.record():
        net(x)
    mm = net.running_mean.data().asnumpy()
    assert onp.abs(mm).sum() > 0  # moved off zero


def test_batchnorm_moving_stats_update_hybridized():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(onp.random.randn(8, 3, 4, 4).astype(onp.float32) * 5 + 2)
    with mx.autograd.record():
        net(x)
    mm = net.running_mean.data().asnumpy()
    assert onp.abs(mm).sum() > 0
    # eval mode: stats stay fixed
    before = net.running_mean.data().asnumpy().copy()
    net(x)
    onp.testing.assert_allclose(net.running_mean.data().asnumpy(), before)


def test_conv2d_deferred_init():
    net = nn.Conv2D(8, 3, padding=1)
    net.initialize()
    x = mx.nd.array(onp.random.randn(2, 5, 9, 9).astype(onp.float32))
    out = net(x)
    assert out.shape == (2, 8, 9, 9)
    assert net.weight.shape == (8, 5, 3, 3)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=3), nn.Dense(2, in_units=8))
    net.initialize()
    f = str(tmp_path / "x.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, in_units=3), nn.Dense(2, in_units=8))
    net2.load_parameters(f)
    onp.testing.assert_allclose(net[0].weight.data().asnumpy(),
                                net2[0].weight.data().asnumpy())


def test_losses():
    pred = mx.nd.array(onp.random.randn(4, 5).astype(onp.float32))
    label = mx.nd.array(onp.array([0, 2, 1, 4], onp.float32))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    # numpy reference
    p = pred.asnumpy()
    logp = p - p.max(-1, keepdims=True)
    logp = logp - onp.log(onp.exp(logp).sum(-1, keepdims=True))
    expect = -logp[onp.arange(4), label.asnumpy().astype(int)]
    onp.testing.assert_allclose(l.asnumpy(), expect, rtol=1e-4)

    l2 = gluon.loss.L2Loss()(pred, pred * 0 + 1.0)
    expect2 = 0.5 * ((p - 1.0) ** 2).mean(-1)
    onp.testing.assert_allclose(l2.asnumpy(), expect2, rtol=1e-5)

    l1 = gluon.loss.L1Loss()(pred, pred * 0)
    onp.testing.assert_allclose(l1.asnumpy(), onp.abs(p).mean(-1), rtol=1e-5)


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array(onp.ones((4, 2), onp.float32))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=4)
    # dL/dw = sum over batch of x = [4,4]; /batch_size=1 each; w = 1-0.1
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((1, 2), 0.9, onp.float32),
                                rtol=1e-6)


def test_trainer_full_loop_decreases_loss():
    mx.random.seed(42)
    rs = onp.random.RandomState(1)
    x = mx.nd.array(rs.randn(64, 10).astype(onp.float32))
    true_w = rs.randn(10, 1).astype(onp.float32)
    y = mx.nd.array(rs.randn(64, 1).astype(onp.float32) * 0.01
                    + x.asnumpy() @ true_w)
    net = nn.Dense(1, in_units=10)
    net.initialize(init=mx.init.Normal(0.1))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    losses = []
    for _ in range(60):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size=64)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.1


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = mx.nd.array(onp.ones((2, 2), onp.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    trainer.step(1)
    f = str(tmp_path / "t.states")
    trainer.save_states(f)
    trainer.load_states(f)
    assert trainer._optimizer.num_update == 1


def test_update_on_kvstore_dist_semantics():
    """dist_sync: optimizer runs inside the store (PS-server semantics)."""
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="dist_sync")
    x = mx.nd.array(onp.ones((4, 2), onp.float32))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=4)
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((1, 2), 0.9, onp.float32),
                                rtol=1e-6)


def test_grad_clipping_pattern():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0}, kvstore=None)
    x = mx.nd.array(onp.full((1, 2), 100.0, onp.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    grads = [p.grad() for p in net.collect_params().values()
             if p.grad_req != "null"]
    total = float(sum((g.norm() ** 2).asnumpy() for g in grads) ** 0.5)
    scale = min(1.0, 1.0 / total)
    for g in grads:
        g *= scale
    trainer.update(batch_size=1)
    w = net.weight.data().asnumpy()
    assert onp.linalg.norm(onp.ones((1, 2)) - w) <= 1.0 + 1e-4
