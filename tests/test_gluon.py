"""Gluon Block/HybridBlock/Parameter tests (mirrors reference
tests/python/unittest/test_gluon.py strategy, SURVEY.md §7)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(4, 3))
    p.initialize(init=mx.init.Xavier())
    assert p.data().shape == (4, 3)
    assert p.grad().shape == (4, 3)
    assert float(p.grad().asnumpy().sum()) == 0.0


def test_parameter_deferred():
    p = gluon.Parameter("weight", shape=(4, 0), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(gluon.DeferredInitializationError):
        p.data()
    p.shape = (4, 7)
    p._finish_deferred_init()
    assert p.data().shape == (4, 7)


def test_dense_forward_matches_numpy():
    net = nn.Dense(5, in_units=3, use_bias=True)
    net.initialize()
    x = mx.nd.array(onp.random.randn(2, 3).astype(onp.float32))
    out = net(x)
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    expect = x.asnumpy() @ w.T + b
    onp.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5)


def test_sequential_and_collect_params():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    x = mx.nd.array(onp.random.randn(4, 3).astype(onp.float32))
    assert net(x).shape == (4, 2)
    params = net.collect_params()
    assert len(params) == 4
    weights = net.collect_params(".*weight")
    assert len(weights) == 2


def test_hybridize_matches_imperative():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(onp.random.randn(5, 7).astype(onp.float32))
    out_imp = net(x).asnumpy()
    net.hybridize()
    out_hyb = net(x).asnumpy()
    onp.testing.assert_allclose(out_imp, out_hyb, rtol=1e-5, atol=1e-6)


def test_hybridize_grads_match():
    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
        return net

    mx.random.seed(7)
    net = build()
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0).randn(6, 4).astype(onp.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_imp = net[0].weight.grad().asnumpy().copy()

    net.hybridize()
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_hyb = net[0].weight.grad().asnumpy()
    onp.testing.assert_allclose(g_imp, g_hyb, rtol=1e-4, atol=1e-5)


def test_batchnorm_moving_stats_update():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = mx.nd.array(onp.random.randn(8, 3, 4, 4).astype(onp.float32) * 5 + 2)
    with mx.autograd.record():
        net(x)
    mm = net.running_mean.data().asnumpy()
    assert onp.abs(mm).sum() > 0  # moved off zero


def test_batchnorm_moving_stats_update_hybridized():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(onp.random.randn(8, 3, 4, 4).astype(onp.float32) * 5 + 2)
    with mx.autograd.record():
        net(x)
    mm = net.running_mean.data().asnumpy()
    assert onp.abs(mm).sum() > 0
    # eval mode: stats stay fixed
    before = net.running_mean.data().asnumpy().copy()
    net(x)
    onp.testing.assert_allclose(net.running_mean.data().asnumpy(), before)


def test_conv2d_deferred_init():
    net = nn.Conv2D(8, 3, padding=1)
    net.initialize()
    x = mx.nd.array(onp.random.randn(2, 5, 9, 9).astype(onp.float32))
    out = net(x)
    assert out.shape == (2, 8, 9, 9)
    assert net.weight.shape == (8, 5, 3, 3)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=3), nn.Dense(2, in_units=8))
    net.initialize()
    f = str(tmp_path / "x.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, in_units=3), nn.Dense(2, in_units=8))
    net2.load_parameters(f)
    onp.testing.assert_allclose(net[0].weight.data().asnumpy(),
                                net2[0].weight.data().asnumpy())


def test_losses():
    pred = mx.nd.array(onp.random.randn(4, 5).astype(onp.float32))
    label = mx.nd.array(onp.array([0, 2, 1, 4], onp.float32))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    # numpy reference
    p = pred.asnumpy()
    logp = p - p.max(-1, keepdims=True)
    logp = logp - onp.log(onp.exp(logp).sum(-1, keepdims=True))
    expect = -logp[onp.arange(4), label.asnumpy().astype(int)]
    onp.testing.assert_allclose(l.asnumpy(), expect, rtol=1e-4)

    l2 = gluon.loss.L2Loss()(pred, pred * 0 + 1.0)
    expect2 = 0.5 * ((p - 1.0) ** 2).mean(-1)
    onp.testing.assert_allclose(l2.asnumpy(), expect2, rtol=1e-5)

    l1 = gluon.loss.L1Loss()(pred, pred * 0)
    onp.testing.assert_allclose(l1.asnumpy(), onp.abs(p).mean(-1), rtol=1e-5)


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array(onp.ones((4, 2), onp.float32))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=4)
    # dL/dw = sum over batch of x = [4,4]; /batch_size=1 each; w = 1-0.1
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((1, 2), 0.9, onp.float32),
                                rtol=1e-6)


def test_trainer_full_loop_decreases_loss():
    mx.random.seed(42)
    rs = onp.random.RandomState(1)
    x = mx.nd.array(rs.randn(64, 10).astype(onp.float32))
    true_w = rs.randn(10, 1).astype(onp.float32)
    y = mx.nd.array(rs.randn(64, 1).astype(onp.float32) * 0.01
                    + x.asnumpy() @ true_w)
    net = nn.Dense(1, in_units=10)
    net.initialize(init=mx.init.Normal(0.1))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    losses = []
    for _ in range(60):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size=64)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.1


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = mx.nd.array(onp.ones((2, 2), onp.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    trainer.step(1)
    f = str(tmp_path / "t.states")
    trainer.save_states(f)
    trainer.load_states(f)
    assert trainer._optimizer.num_update == 1


def test_update_on_kvstore_dist_semantics():
    """dist_sync: optimizer runs inside the store (PS-server semantics)."""
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="dist_sync")
    x = mx.nd.array(onp.ones((4, 2), onp.float32))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=4)
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((1, 2), 0.9, onp.float32),
                                rtol=1e-6)


def test_grad_clipping_pattern():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0}, kvstore=None)
    x = mx.nd.array(onp.full((1, 2), 100.0, onp.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    grads = [p.grad() for p in net.collect_params().values()
             if p.grad_req != "null"]
    total = float(sum((g.norm() ** 2).asnumpy() for g in grads) ** 0.5)
    scale = min(1.0, 1.0 / total)
    for g in grads:
        g *= scale
    trainer.update(batch_size=1)
    w = net.weight.data().asnumpy()
    assert onp.linalg.norm(onp.ones((1, 2)) - w) <= 1.0 + 1e-4


# --------------------------------------------------------------------------- #
# jit-by-default trace cache (non-hybridized inference loops)
# --------------------------------------------------------------------------- #

def _jit_default_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8))
    net.initialize(mx.init.Normal(0.1))
    return net


def test_jit_by_default_inference_uses_trace_cache():
    """A non-hybridized HybridBlock in a predict loop routes through the
    CachedOp trace cache automatically — and matches the imperative
    result exactly."""
    net = _jit_default_net()
    x = mx.nd.array(onp.random.RandomState(0).rand(4, 12)
                    .astype("float32"))
    y = net(x)
    assert net._cached_op is not None          # trace cache engaged
    assert net._auto_jit is True
    # second call reuses the same jitted executable (no retrace)
    op = net._cached_op
    y2 = net(x)
    assert net._cached_op is op
    assert op._get_jitted(False)._cache_size() == 1
    onp.testing.assert_array_equal(y.asnumpy(), y2.asnumpy())


def test_jit_by_default_parity_with_env_hatch(monkeypatch):
    net = _jit_default_net()
    x = mx.nd.array(onp.random.RandomState(1).rand(3, 12)
                    .astype("float32"))
    y_jit = net(x).asnumpy()
    monkeypatch.setenv("MXNET_JIT_BY_DEFAULT", "0")
    net2 = _jit_default_net()
    for p, q in zip(net.collect_params().values(),
                    net2.collect_params().values()):
        q.set_data(p.data())
    y_imp = net2(x).asnumpy()
    assert net2._cached_op is None             # hatch keeps it imperative
    onp.testing.assert_allclose(y_jit, y_imp, rtol=1e-6, atol=1e-6)


def test_jit_by_default_skips_autograd_recording():
    """The training path keeps exact imperative semantics — recording a
    non-hybridized forward must not engage the trace cache."""
    net = _jit_default_net()
    x = mx.nd.array(onp.ones((2, 12), onp.float32))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    assert net._cached_op is None
    assert all(onp.isfinite(p.grad().asnumpy()).all()
               for p in net.collect_params().values()
               if p.grad_req != "null")


def test_jit_by_default_hybridize_false_opts_out():
    net = _jit_default_net()
    net.hybridize(False)
    x = mx.nd.array(onp.ones((2, 12), onp.float32))
    net(x)
    assert net._cached_op is None
    assert net._auto_jit is False


def test_jit_by_default_trace_hostile_falls_back():
    """A forward with value-dependent Python control flow cannot trace;
    it must fall back to imperative execution once and never retry."""
    class Hostile(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.traces = 0

        def hybrid_forward(self, F, x):
            self.traces += 1
            if float(x.sum().asnumpy().item()) > -1e30:  # host sync
                return x * 2
            return x

    h = Hostile()
    h.initialize()
    x = mx.nd.array(onp.ones((2, 3), onp.float32))
    y = h(x)
    onp.testing.assert_allclose(y.asnumpy(), 2 * onp.ones((2, 3)))
    assert h._auto_jit is False
    runs_after_fallback = h.traces
    h(x)                                      # imperative, no retrace try
    assert h._auto_jit is False
    assert h.traces == runs_after_fallback + 1


def test_jit_by_default_hook_error_propagates():
    """A raising forward hook is a USER error: it must propagate, not be
    swallowed as a trace failure (which would re-run the whole forward
    imperatively and permanently disable the jit)."""
    net = _jit_default_net()
    x = mx.nd.array(onp.ones((2, 12), onp.float32))
    calls = []
    net.register_forward_hook(lambda blk, args, out: calls.append(1))
    net(x)
    assert net._auto_jit is True and calls == [1]

    boom = RuntimeError("hook boom")

    def bad_hook(blk, args, out):
        raise boom

    net2 = _jit_default_net()
    net2.register_forward_hook(bad_hook)
    with pytest.raises(RuntimeError, match="hook boom"):
        net2(x)
    # the trace itself succeeded — the hook error must not flip the
    # block back to permanent imperative execution
    assert net2._auto_jit is True

    net3 = _jit_default_net()
    net3.register_forward_pre_hook(lambda blk, args: (_ for _ in ()).throw(boom))
    with pytest.raises(RuntimeError, match="hook boom"):
        net3(x)
    assert net3._auto_jit is None             # untried, retries next call
    net3._forward_pre_hooks.clear()
    net3(x)
    assert net3._auto_jit is True


def test_jit_by_default_real_error_does_not_pin_imperative():
    """A genuinely bad input raises in the trace AND the imperative
    re-run: the error must propagate without permanently disabling the
    jit — a corrected call retries (and gets) the trace cache."""
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=12))
    net.initialize(mx.init.Normal(0.1))
    bad = mx.nd.array(onp.ones((2, 7), onp.float32))   # wrong feature dim
    with pytest.raises(Exception):
        net(bad)
    assert net._auto_jit is None              # untried, not pinned off
    good = mx.nd.array(onp.ones((2, 12), onp.float32))
    net(good)
    assert net._auto_jit is True
    assert net._cached_op is not None


def test_cached_op_trace_serialized_by_trace_lock():
    """_CachedOp.__call__ must hold the shared trace lock: a concurrent
    trace (e.g. the decode server retracing the same model on its own
    thread) swaps shared Parameters to tracers, so an unlocked forward
    would capture a leaked tracer."""
    import threading
    from mxnet_tpu.gluon.parameter import _TRACE_LOCK

    net = _jit_default_net()
    x = mx.nd.array(onp.ones((2, 12), onp.float32))
    done = threading.Event()
    out = []

    def fwd():
        out.append(net(x).asnumpy())
        done.set()

    with _TRACE_LOCK:
        t = threading.Thread(target=fwd, daemon=True)
        t.start()
        assert not done.wait(0.5)             # first call (trace) blocks
    assert done.wait(30)                      # released -> completes
    t.join(30)
    assert net._auto_jit is True and out[0].shape == (2, 8)
