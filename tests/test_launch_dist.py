"""Multi-process pod runtime tests (ISSUE 19): a REAL
``jax.distributed`` global mesh over N launched CPU processes as the
CI stand-in for a TPU pod.

Four contracts are pinned here:

  * **Parity** — an N-process fused-step pretrain (grad reduction
    crossing process boundaries through gloo collectives) reproduces
    the single-process virtual-mesh loss curve numerically, at one
    compile and one executable dispatch per step per process.
  * **Rendezvous chaos** — ``fault_point("dist.init")`` inside the
    bounded-retry init loop: a raise-fault is retried (attempt count
    lands in the ``dist_init`` telemetry event), a kill-fault turns
    into a supervised ``worker_dead``.
  * **Elastic resume** — killing one rank mid-run under
    ``tools/launch.py --elastic`` re-forms the pod on N-1 ranks, which
    resume from the newest complete checkpoint with the SAME global
    batch cursor: every loss printed by any generation matches the
    uninterrupted single-process truth at the same step.
  * **Pod telemetry** — per-rank ``MXNET_TELEMETRY_JSONL`` recordings
    merged by ``telemetry_report --pod`` answer "which host retraced /
    which host is over its HBM budget" from rank-tagged events.

The launched workers run ``tests/fixtures/dist_pretrain.py``; see its
docstring for the determinism contract.
"""
import json
import os
import re
import socket
import subprocess
import sys

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "dist_pretrain.py")
LAUNCH = os.path.join(REPO, "tools", "launch.py")
REPORT = os.path.join(REPO, "tools", "telemetry_report.py")

STEP_RE = re.compile(
    r"\[rank (\d+) gen (\d+)\] STEP (\d+) loss=([0-9.eE+-]+)")
DONE_RE = re.compile(
    r"\[rank (\d+) gen (\d+)\] DONE steps=(\d+) world=(\d+) "
    r"compiles=(\d+) dispatches=(\d+)")


def _env(**extra):
    """Subprocess env: single CPU device per process (the pod stand-in
    must NOT inherit pytest's 8-virtual-device XLA_FLAGS)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_FAULT_INJECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env.update(extra)
    return env


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run(cmd, timeout, **extra_env):
    return subprocess.run(
        [sys.executable] + cmd, capture_output=True, text=True,
        timeout=timeout, env=_env(**extra_env), cwd=REPO)


def _losses(out):
    """{step: [loss, ...]} from every rank/generation's STEP lines."""
    got = {}
    for m in STEP_RE.finditer(out):
        got.setdefault(int(m.group(3)), []).append(float(m.group(4)))
    return got


def _done(out):
    """[(rank, gen, steps, world, compiles, dispatches), ...]"""
    return [tuple(int(g) for g in m.groups())
            for m in DONE_RE.finditer(out)]


class TestPodParity:
    def test_two_process_parity_smoke(self, tmp_path):
        """Acceptance gate: 2-process pod pretrain via tools/launch.py
        matches the single-process virtual-mesh loss curve, with ONE
        compile (the ``_cache_size()==1`` discipline) and one dispatch
        per step on every process."""
        steps = 4
        single = _run([FIXTURE, "--steps", str(steps), "--out",
                       str(tmp_path / "single_RANK.npz")], timeout=150)
        assert single.returncode == 0, single.stderr[-2000:]
        pod = _run([LAUNCH, "-n", "2", "--launcher", "local",
                    sys.executable, FIXTURE, "--steps", str(steps),
                    "--out", str(tmp_path / "pod_RANK.npz")],
                   timeout=200)
        assert pod.returncode == 0, \
            pod.stdout[-2000:] + pod.stderr[-2000:]

        ref, got = _losses(single.stdout), _losses(pod.stdout)
        assert sorted(ref) == sorted(got) == list(range(steps))
        for step, vals in got.items():
            assert len(vals) == 2, (step, vals)  # both ranks spoke
            for v in vals:
                assert v == pytest.approx(ref[step][0], abs=1e-6), \
                    (step, v, ref[step][0])

        # one executable per step per process: exactly 1 compile and
        # `steps` dispatches on each rank
        done = _done(pod.stdout)
        assert sorted(d[0] for d in done) == [0, 1]
        for rank, _gen, nsteps, world, compiles, dispatches in done:
            assert world == 2
            assert compiles == 1, (rank, compiles)
            assert dispatches == steps == nsteps

        # the trained params agree across arms and are identical
        # across ranks (the pod's replicated state never diverges)
        s0 = onp.load(tmp_path / "single_0.npz")
        p0 = onp.load(tmp_path / "pod_0.npz")
        p1 = onp.load(tmp_path / "pod_1.npz")
        for k in s0.files:
            if k.startswith("param:"):
                onp.testing.assert_allclose(s0[k], p0[k], atol=1e-6)
                onp.testing.assert_array_equal(p0[k], p1[k])


class TestDistInitChaos:
    def test_raise_fault_is_retried(self, tmp_path):
        """A transient rendezvous failure (raise-fault on the 1st
        ``dist.init`` hit) is absorbed by the bounded-retry loop: the
        run succeeds and the ``dist_init`` event records attempt 2."""
        jsonl = tmp_path / "tel.jsonl"
        r = _run([FIXTURE, "--steps", "1"], timeout=150,
                 MXNET_COORDINATOR=f"127.0.0.1:{_free_port()}",
                 MXNET_NUM_WORKERS="1", MXNET_WORKER_ID="0",
                 MXNET_INIT_RETRIES="3", MXNET_INIT_TIMEOUT="30",
                 MXNET_FAULT_INJECT="dist.init:raise:1",
                 MXNET_TELEMETRY_JSONL=str(jsonl))
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        events = [json.loads(l) for l in
                  jsonl.read_text().splitlines() if l.strip()]
        inits = [e for e in events if e.get("kind") == "dist_init"]
        assert len(inits) == 1 and inits[0]["attempts"] == 2, inits
        assert any(e.get("kind") == "fault_injected" and
                   e.get("site") == "dist.init" for e in events)

    def test_kill_fault_is_worker_dead(self, tmp_path):
        """A rank dying IN rendezvous is a supervised worker_dead, not
        a hang: the launcher tears the pod down and exits nonzero."""
        r = _run([LAUNCH, "-n", "2", "--launcher", "local",
                  "--heartbeat-timeout", "10",
                  "--heartbeat-interval", "0.5",
                  sys.executable, FIXTURE, "--steps", "2",
                  "--fault", "0=dist.init:kill:1", "--fault-rank", "1"],
                 timeout=200)
        assert r.returncode != 0
        assert "rank 1" in r.stdout, r.stdout[-2000:]


class TestElasticResume:
    def test_kill_one_rank_completes_on_smaller_mesh(self, tmp_path):
        """The headline elastic acceptance: rank 1 of 2 is killed mid
        run; under ``--elastic --restarts 1`` the supervisor re-forms
        the pod on ONE rank, which resumes from its newest complete
        checkpoint and finishes — and every loss any generation
        printed matches the uninterrupted single-process truth at the
        same global step.  Then ``telemetry_report --pod`` over the
        per-rank recordings re-tells the story: both ranks' compiles,
        rank 1's injected fault, the supervisor's pod_restart, and
        rank 0's saves."""
        steps = 8
        truth = _run([FIXTURE, "--steps", str(steps)], timeout=150)
        assert truth.returncode == 0, truth.stderr[-2000:]
        ref = _losses(truth.stdout)

        ck, tel = tmp_path / "ck", tmp_path / "tel"
        r = _run([LAUNCH, "-n", "2", "--launcher", "local",
                  "--elastic", "--restarts", "1",
                  "--restart-backoff", "0.2",
                  "--heartbeat-timeout", "8",
                  "--heartbeat-interval", "0.5",
                  "--checkpoint-dir", str(ck),
                  "--telemetry-dir", str(tel),
                  sys.executable, FIXTURE, "--steps", str(steps),
                  "--out", str(tmp_path / "el_RANK.npz"),
                  "--fault", "0=data.next:kill:5", "--fault-rank", "1"],
                 timeout=400)
        assert r.returncode == 0, \
            r.stdout[-3000:] + r.stderr[-2000:]
        assert "elastic: re-forming on 1 rank(s)" in \
            r.stdout + r.stderr

        # the shrunken generation really ran single-process to the end
        done = _done(r.stdout)
        gen1 = [d for d in done if d[1] == 1]
        assert len(gen1) == 1 and gen1[0][0] == 0 and gen1[0][3] == 1, \
            done
        assert gen1[0][4] == 1  # still one executable after re-form
        finals = _losses(r.stdout)
        assert max(finals) == steps - 1  # the run reached the last step

        # loss-curve pinning: every printed loss — 2-rank generation,
        # re-executed steps, 1-rank generation — matches the truth
        for step, vals in finals.items():
            for v in vals:
                assert v == pytest.approx(ref[step][0], abs=1e-6), \
                    (step, v, ref[step][0])

        # re-verify through the pod telemetry view
        rep = _run([REPORT, str(tel), "--pod", "--json"], timeout=60)
        assert rep.returncode == 0, rep.stderr[-2000:]
        pod = {row["rank"]: row
               for row in json.loads(rep.stdout)["pod"]}
        assert 0 in pod and 1 in pod, sorted(pod, key=str)
        assert pod[1]["faults"] == 1            # the injected kill
        assert pod[0]["saves"] >= steps         # rank 0 checkpointed
        assert pod[0]["dist_inits"] == 2        # gen 0 + elastic gen 1
        assert pod[1]["dist_inits"] == 1
        # the supervisor's own recording joined the pod dir
        assert any(e.get("kind") == "pod_restart"
                   for e in _events(tel / "launcher.jsonl"))

    def test_resume_on_different_world_size_requires_elastic(
            self, tmp_path):
        """A silently resized pod is refused: a checkpoint written by
        2 ranks only resumes on 1 rank when MXNET_ELASTIC=1."""
        ck = tmp_path / "ck"
        r = _run([LAUNCH, "-n", "2", "--launcher", "local",
                  "--checkpoint-dir", str(ck),
                  sys.executable, FIXTURE, "--steps", "2"],
                 timeout=200)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        refused = _run([FIXTURE, "--steps", "4", "--dir",
                        str(ck)], timeout=150)
        assert refused.returncode == 3, refused.stdout[-2000:]
        assert "MXNET_ELASTIC=1" in refused.stderr
        resumed = _run([FIXTURE, "--steps", "4", "--dir", str(ck)],
                       timeout=150, MXNET_ELASTIC="1")
        assert resumed.returncode == 0, resumed.stderr[-2000:]
        assert "resumed at global batch 2" in resumed.stdout


def _events(path):
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        return [json.loads(l) for l in fh if l.strip()]


class TestDistBenchSmoke:
    def test_dist_bench_smoke(self):
        """Both arms produce rows at the tier-1 geometry, and the pod
        arm holds the one-dispatch-per-step / zero-steady-compile
        discipline (dist_bench exits nonzero otherwise)."""
        r = _run([os.path.join(REPO, "benchmark", "dist_bench.py"),
                  "--smoke", "--steps", "4"], timeout=300)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        rows = [json.loads(l) for l in r.stdout.splitlines()
                if l.startswith("{")]
        modes = {row["mode"]: row for row in rows}
        assert {"single", "pod", "pod_rank0", "pod_rank1"} <= \
            set(modes)
        assert modes["pod"]["dispatches_per_step"] == 1.0
        assert modes["pod"]["compiles_steady"] == 0
        assert modes["single"]["tokens_per_sec"] > 0
        assert modes["pod"]["tokens_per_sec"] > 0


class TestPodReport:
    """`telemetry_report --pod` verdict logic on synthetic per-rank
    recordings — which host retraced, which host is over its HBM
    budget — without spawning a pod."""

    def _write(self, d, rank, events):
        with open(os.path.join(d, f"rank{rank}.jsonl"), "w") as fh:
            for e in events:
                fh.write(json.dumps(e) + "\n")

    @pytest.fixture()
    def pod_dir(self, tmp_path):
        d = str(tmp_path / "pod")
        os.makedirs(d)
        self._write(d, 0, [
            {"ts": 1.0, "kind": "compile", "rank": 0,
             "site": "step", "wall_s": 0.5, "retrace": False},
            {"ts": 2.0, "kind": "device_memory", "rank": 0,
             "subsystem": "train", "key": "params", "bytes": 100},
            {"ts": 3.0, "kind": "device_memory", "rank": 0,
             "subsystem": "train", "key": "params", "bytes": 50},
        ])
        self._write(d, 1, [
            {"ts": 1.5, "kind": "compile", "rank": 1,
             "site": "step", "wall_s": 0.5, "retrace": False},
            {"ts": 2.5, "kind": "compile", "rank": 1,
             "site": "step", "wall_s": 0.7, "retrace": True},
            {"ts": 2.6, "kind": "device_memory", "rank": 1,
             "subsystem": "train", "key": "params", "bytes": 600},
            {"ts": 2.7, "kind": "device_memory", "rank": 1,
             "subsystem": "serve", "key": "kv", "bytes": 600},
        ])
        return d

    def test_identifies_retraced_and_over_budget_host(self, pod_dir):
        from tools.telemetry_report import load_pod, pod_summary

        pod = {row["rank"]: row for row in
               pod_summary(load_pod(pod_dir), hbm_budget=1000)}
        assert pod[0]["retraces"] == 0
        assert pod[1]["retraces"] == 1
        assert pod[1]["retrace_sites"] == ["step"]
        # rank 0's peak is the CONCURRENT max (100), not the sum of
        # samples over time; rank 1's two live gauges add up
        assert pod[0]["peak_device_bytes"] == 100
        assert pod[1]["peak_device_bytes"] == 1200
        assert not pod[0]["over_hbm_budget"]
        assert pod[1]["over_hbm_budget"]

    def test_cli_pod_json(self, pod_dir):
        r = _run([REPORT, pod_dir, "--pod", "--json",
                  "--hbm-budget", "1K"], timeout=60)
        assert r.returncode == 0, r.stderr[-2000:]
        pod = {row["rank"]: row for row in json.loads(r.stdout)["pod"]}
        assert pod[1]["over_hbm_budget"] is True
        assert pod[0]["over_hbm_budget"] is False
