"""Operand-schema registry pins (ISSUE 20): the declarative source of
truth in ``mxnet_tpu/serve/schema.py`` must keep producing EXACTLY the
positional facts the pre-refactor engine hand-counted — donation index
pairs, the 29-byte slot-state total, meta-row layouts — and its
build-time validation must refuse a drifted signature instead of
letting XLA donate the wrong buffer (the PR-18 recycled-page shape).
"""
import pytest

from mxnet_tpu.serve import schema

# the hand-counted literals the five jits carried before the registry
# landed — the refactor must be a pure re-derivation, not a re-pricing
_PRE_REFACTOR_DONATE = {
    "step": (5, 6),
    "admit": (6, 7),
    "hit": (5, 6),
    "chunk": (8, 9),
    "verify": (7, 8),
}
_PRE_REFACTOR_ARITY = {
    "step": 14, "admit": 15, "hit": 14, "chunk": 17, "verify": 16,
}


def _fn_with(params):
    ns = {}
    exec("def f({}):\n    return None".format(", ".join(params)), ns)
    return ns["f"]


class TestRegistryPins:
    def test_executable_set_is_the_five_pool_programs(self):
        assert set(schema.executable_names()) == {
            "step", "admit", "hit", "chunk", "verify"}

    def test_donate_indices_match_pre_refactor_literals(self):
        for name, want in _PRE_REFACTOR_DONATE.items():
            assert schema.donate_argnums(name) == want, name

    def test_arities_match_pre_refactor_signatures(self):
        for name, want in _PRE_REFACTOR_ARITY.items():
            assert schema.arity(name) == want, name

    def test_every_executable_donates_exactly_the_kv_pools(self):
        for name in schema.executable_names():
            assert schema.donated_operands(name) == ("kp", "vp"), name

    def test_state_operands_are_the_nine_pool_columns(self):
        assert schema.state_operands() == (
            "kp", "vp", "pos", "tok", "active", "stop", "keys", "dl",
            "spec")
        assert schema.state_arity() == 9
        # every executable's operand list ENDS with the state tuple —
        # the *state splat at dispatch sites depends on it
        for name in schema.executable_names():
            assert schema.operands(name)[-9:] == schema.state_operands()

    def test_slot_state_prices_to_29_bytes(self):
        assert schema.slot_state_bytes() == 29

    def test_unknown_executable_is_an_error(self):
        with pytest.raises(ValueError):
            schema.operands("prefill")


class TestJitDonateValidation:
    def test_matching_signature_yields_registry_indices(self):
        for name in schema.executable_names():
            fn = _fn_with(schema.operands(name))
            assert schema.jit_donate(name, fn) == \
                _PRE_REFACTOR_DONATE[name], name

    def test_inserted_operand_without_schema_update_raises(self):
        """The PR-18 shape at build time: a parameter lands in the
        signature, the schema does not move, and the derivation refuses
        to hand XLA a donation map it cannot vouch for."""
        params = list(schema.operands("admit"))
        params.insert(2, "scratch_rows")
        with pytest.raises(ValueError, match="drifted"):
            schema.jit_donate("admit", _fn_with(params))

    def test_dropped_operand_raises(self):
        params = [p for p in schema.operands("step") if p != "sw"]
        assert len(params) == schema.arity("step") - 1
        with pytest.raises(ValueError, match="drifted"):
            schema.jit_donate("step", _fn_with(params))

    def test_renamed_donated_operand_raises(self):
        params = [("kpages" if p == "kp" else p)
                  for p in schema.operands("verify")]
        with pytest.raises(ValueError, match="drifted"):
            schema.jit_donate("verify", _fn_with(params))


class TestMetaLayouts:
    def test_widths_match_pre_refactor_row_shapes(self):
        assert schema.meta_width("admit") == 6
        assert schema.meta_width("hit") == 7
        assert schema.meta_width("chunk") == 8
        assert schema.meta_width("step") == 0
        assert schema.meta_width("verify") == 0

    def test_meta_row_roundtrips_through_meta_col(self):
        fields = schema.meta_fields("admit")
        vals = {f: i * 10 for i, f in enumerate(fields)}
        row = schema.meta_row("admit", **vals)
        assert len(row) == schema.meta_width("admit")
        for f in fields:
            assert row[schema.meta_col("admit", f)] == vals[f]

    def test_meta_cols_is_the_full_index_map(self):
        cols = schema.meta_cols("chunk")
        assert set(cols) == set(schema.meta_fields("chunk"))
        assert sorted(cols.values()) == list(
            range(schema.meta_width("chunk")))

    def test_meta_row_missing_field_raises(self):
        vals = {f: 0 for f in schema.meta_fields("hit")[1:]}
        with pytest.raises(ValueError):
            schema.meta_row("hit", **vals)

    def test_meta_row_extra_field_raises(self):
        vals = {f: 0 for f in schema.meta_fields("hit")}
        vals["ttl"] = 3
        with pytest.raises(ValueError):
            schema.meta_row("hit", **vals)

    def test_unknown_meta_field_raises(self):
        with pytest.raises(ValueError):
            schema.meta_col("admit", "ttl")


class TestKvPagePricing:
    def test_int8_page_bytes_formula(self):
        # codes: NL * 2 * KV * page * D int8 + per-page scales:
        # NL * 2 * KV * float32 — the ledger's resident-page price
        nl, kv, page, d = 4, 2, 16, 64
        assert schema.kv_page_int8_bytes(nl, kv, page, d) == \
            2 * nl * kv * (page * d * 1 + 4)

    def test_kv_dtype_pins_match_decoding(self):
        """decoding.py cannot import serve (cycle), so it carries its
        own dtype constants — these pins are the contract that they
        stay in lockstep with the schema's declaration."""
        jnp = pytest.importorskip("jax.numpy")
        from mxnet_tpu.models import decoding
        assert jnp.dtype(decoding._KV_CODE_DTYPE).name == \
            schema.KV_PAGE_INT8["codes"]
        assert jnp.dtype(decoding._KV_SCALE_DTYPE).name == \
            schema.KV_PAGE_INT8["scales"]
        scale_bytes = jnp.dtype(decoding._KV_SCALE_DTYPE).itemsize
        assert schema.kv_page_int8_bytes(1, 1, 1, 1) == \
            2 * (1 + scale_bytes)
