"""AMP tests (reference tests/python/gpu/test_amp.py coverage;
SURVEY.md §3.2 "AMP")."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon
from mxnet_tpu.base import MXNetError


@pytest.fixture
def amp_env():
    amp.init(target_dtype="bfloat16")
    yield
    amp._uninit()


class TestCastPolicy:
    def test_target_ops_cast_to_bf16(self, amp_env):
        a = mx.nd.array(onp.random.rand(4, 8).astype(onp.float32))
        b = mx.nd.array(onp.random.rand(8, 5).astype(onp.float32))
        out = mx.nd.dot(a, b)
        assert str(out.dtype) == "bfloat16"

    def test_fp32_ops_stay_fp32(self, amp_env):
        x = mx.nd.array(onp.random.rand(4, 8).astype(onp.float32))
        low = x.astype("bfloat16")
        out = mx.nd.softmax(low)
        assert str(out.dtype) == "float32"

    def test_widest_cast(self, amp_env):
        lo = mx.nd.array(onp.ones((3,), onp.float32)).astype("bfloat16")
        hi = mx.nd.array(onp.ones((3,), onp.float32))
        out = mx.nd.broadcast_add(lo, hi)
        assert str(out.dtype) == "float32"

    def test_double_init_is_noop(self, amp_env):
        amp.init()  # second call must not re-wrap
        a = mx.nd.array(onp.random.rand(2, 2).astype(onp.float32))
        assert str(mx.nd.dot(a, a).dtype) == "bfloat16"

    def test_init_rejects_bad_dtype(self):
        with pytest.raises(MXNetError):
            amp.init(target_dtype="int8")


class TestLossScaler:
    def test_dynamic_scaling(self):
        ls = amp.LossScaler(init_scale=1024, scale_window=2)
        ls.update_scale(False)
        ls.update_scale(False)
        assert ls.loss_scale == 2048
        ls.update_scale(True)
        assert ls.loss_scale == 1024

    def test_overflow_detection(self):
        from mxnet_tpu.gluon import Parameter
        p = Parameter("w", shape=(3,))
        p.initialize()
        p._data._grad = mx.nd.array(onp.array([1.0, onp.inf, 2.0],
                                              onp.float32))
        ls = amp.LossScaler()
        assert ls.has_overflow([p])
        p._data._grad = mx.nd.array(onp.ones(3, onp.float32))
        assert not ls.has_overflow([p])


class TestTrainerIntegration:
    def test_fp16_training_with_scaler(self, amp_env):
        net = gluon.nn.Dense(4)
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        x = mx.nd.array(onp.random.rand(8, 6).astype(onp.float32))
        y = mx.nd.array(onp.random.rand(8, 4).astype(onp.float32))
        loss_fn = gluon.loss.L2Loss()
        losses = []
        for _ in range(5):
            with autograd.record():
                out = net(x)
                L = loss_fn(out.astype("float32"), y)
            with amp.scale_loss(L, trainer) as scaled:
                scaled.backward()
            trainer.step(8)
            losses.append(float(L.mean().asnumpy()))
        assert losses[-1] < losses[0]

    def test_overflow_skips_update(self, amp_env):
        net = gluon.nn.Dense(2)
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        scaler = trainer._amp_loss_scaler
        scaler.loss_scale = 4.0
        x = mx.nd.array(onp.random.rand(2, 3).astype(onp.float32))
        with autograd.record():
            out = net(x)
            L = (out * out).sum()
        L.backward()
        # poison one gradient with inf
        params = list(net.collect_params().values())
        w = params[0]
        w._data._grad = w.grad() * onp.inf
        before = w.data().asnumpy().copy()
        trainer.step(2)
        onp.testing.assert_array_equal(w.data().asnumpy(), before)
        assert scaler.loss_scale == 2.0  # halved on overflow


class TestConvert:
    def test_convert_hybrid_block(self):
        net = gluon.nn.Dense(4)
        net.initialize(mx.init.Xavier())
        amp.convert_hybrid_block(net, target_dtype="bfloat16")
        x = mx.nd.array(onp.random.rand(2, 3).astype(onp.float32))
        out = net(x.astype("bfloat16"))
        assert str(out.dtype) == "bfloat16"
